"""Determinism rules: sim-reachable code may not observe the host.

Simulation replays bit-identically from a seed only while every input is
loop-derived: virtual time (``loop.now()`` / ``delay()``), forked seeded
RNGs (``loop.random.fork()``), loop-issued UIDs. One ``time.time()`` or
module-level ``random.random()`` in sim-reachable code breaks PR 6's
same-seed byte-identical span guarantee in a way no tier-1 test localizes.

These rules flag *calls*. A bare reference (``now_fn=time.perf_counter``)
is dependency injection — the caller decides which personality's clock to
plug in — and is deliberately allowed.

Host-side tools (fdbmonitor, tcp_soak, …) are exempted via the
``host_only`` manifest in config.json, not ad hoc: the engine never feeds
them to ``scope="sim"`` rules, and `cli lint` prints the manifest so the
exemption stays visible.
"""

from __future__ import annotations

import ast
from typing import Iterator

from .core import Finding, Module, Rule

WALL_CLOCK = {
    "time.time",
    "time.time_ns",
    "time.monotonic",
    "time.monotonic_ns",
    "time.perf_counter",
    "time.perf_counter_ns",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "datetime.datetime.today",
    "datetime.date.today",
}

ENTROPY = {
    "os.urandom",
    "os.getrandom",
    "uuid.uuid1",
    "uuid.uuid4",
    "secrets.token_bytes",
    "secrets.token_hex",
    "secrets.token_urlsafe",
    "secrets.randbits",
    "secrets.randbelow",
    "secrets.choice",
}


def _calls(mod: Module) -> Iterator[tuple[ast.Call, str]]:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            dotted = mod.dotted(node.func)
            if dotted:
                yield node, dotted


class WallClockRule(Rule):
    id = "det-wall-clock"
    title = "wall-clock read in sim-reachable code (use loop.now())"
    scope = "sim"

    def check_module(self, mod: Module, config: dict) -> Iterator[Finding]:
        for node, dotted in _calls(mod):
            if dotted in WALL_CLOCK:
                yield mod.finding(
                    self.id,
                    node,
                    dotted,
                    f"{dotted}() reads the host clock; sim time must come "
                    f"from loop.now() (replay would diverge from its seed)",
                )


class SleepRule(Rule):
    id = "det-sleep"
    title = "time.sleep stalls the deterministic loop (use delay())"
    scope = "sim"

    def check_module(self, mod: Module, config: dict) -> Iterator[Finding]:
        for node, dotted in _calls(mod):
            if dotted == "time.sleep":
                yield mod.finding(
                    self.id,
                    node,
                    dotted,
                    "time.sleep() blocks the single-threaded loop in real "
                    "wall time; use await delay() (virtual time)",
                )


class EntropyRule(Rule):
    id = "det-entropy"
    title = "OS entropy in sim-reachable code (use loop.random)"
    scope = "sim"

    def check_module(self, mod: Module, config: dict) -> Iterator[Finding]:
        for node, dotted in _calls(mod):
            if dotted in ENTROPY:
                yield mod.finding(
                    self.id,
                    node,
                    dotted,
                    f"{dotted}() draws OS entropy; derive ids/bytes from the "
                    f"seeded loop RNG (loop.random / DeterministicRandom.fork)",
                )


class UnseededRandomRule(Rule):
    id = "det-unseeded-random"
    title = "module-level / unseeded random (fork the loop RNG instead)"
    scope = "sim"

    def check_module(self, mod: Module, config: dict) -> Iterator[Finding]:
        for node, dotted in _calls(mod):
            bad = None
            if dotted.startswith("random."):
                tail = dotted[len("random.") :]
                if tail == "Random":
                    if not node.args and not node.keywords:
                        bad = "random.Random() unseeded (OS-entropy default)"
                elif tail == "SystemRandom":
                    bad = "random.SystemRandom is OS entropy by construction"
                elif "." not in tail:  # module-level helpers share one global state
                    bad = f"module-level random.{tail}() uses the global RNG"
            elif dotted.startswith("numpy.random."):
                tail = dotted[len("numpy.random.") :]
                if not (tail == "default_rng" and (node.args or node.keywords)):
                    bad = f"numpy.random.{tail}() global/unseeded numpy RNG"
            if bad:
                yield mod.finding(
                    self.id,
                    node,
                    dotted,
                    f"{bad}; sim code draws from loop.random (seeded, "
                    f"forkable) so failures replay from their seed",
                )


RULES: list[Rule] = [
    WallClockRule(),
    SleepRule(),
    EntropyRule(),
    UnseededRandomRule(),
]
