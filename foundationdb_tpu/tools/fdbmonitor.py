"""fdbmonitor: supervise a machine's fdbserver processes.

The analog of fdbmonitor/fdbmonitor.cpp: read a foundationdb.conf-style
INI, launch one fdbserver per [fdbserver.<N>] section, restart any child
that exits (with backoff doubling up to a cap, reset after a stable run),
forward SIGTERM/SIGINT to the children, and log lifecycle events.

  python -m foundationdb_tpu.tools.fdbmonitor --conffile cluster.conf

Config format (a trimmed foundationdb.conf):

    [general]
    restart_delay = 5
    cluster_coordinators = 127.0.0.1:4500

    [fdbserver.4500]
    role = coordinator
    listen = 127.0.0.1:4500
    datadir = /var/lib/fdbtpu/4500

    [fdbserver.4600]
    listen = 127.0.0.1:4600
    class = storage
    datadir = /var/lib/fdbtpu/4600
"""

from __future__ import annotations

import configparser
import signal
import subprocess
import sys
import time


def build_args(section: dict, general: dict) -> list[str]:
    args = ["--listen", section["listen"]]
    role = section.get("role", "worker")
    args += ["--role", role]
    if role == "worker":
        coords = section.get(
            "coordinators", general.get("cluster_coordinators", "")
        )
        args += ["--coordinators", coords]
        if section.get("class"):
            args += ["--class", section["class"]]
        if section.get("config", general.get("config")):
            args += ["--config", section.get("config", general.get("config"))]
    for key in ("datadir", "zone", "dc", "tracefile"):
        val = section.get(key)
        if val:
            args += [f"--{key}", val]
    return args


class _Child:
    def __init__(self, name: str, args: list[str], restart_delay: float):
        self.name = name
        self.args = args
        self.base_delay = restart_delay
        self.delay = restart_delay
        self.proc: subprocess.Popen = None
        self.started_at = 0.0
        self.restart_at: float = None  # pending-restart deadline

    def start(self):
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "foundationdb_tpu.tools.fdbserver", *self.args]
        )
        self.started_at = time.time()
        print(
            f"fdbmonitor: started {self.name} pid={self.proc.pid}", flush=True
        )

    def poll_and_restart(self):
        # deadline-based, never sleeps: one child's 60s backoff must not
        # stall restarts of the other children or signal handling for the
        # whole window (the monitor loop stays responsive at poll period)
        if self.restart_at is not None:
            if time.time() >= self.restart_at:
                self.restart_at = None
                self.delay = min(self.delay * 2, 60.0)
                self.start()
            return
        if self.proc.poll() is None:
            return
        rc = self.proc.returncode
        ran_for = time.time() - self.started_at
        # a stable run resets the backoff (fdbmonitor's RESET_AFTER)
        if ran_for > 60:
            self.delay = self.base_delay
        print(
            f"fdbmonitor: {self.name} exited rc={rc} after {ran_for:.1f}s; "
            f"restarting in {self.delay:.1f}s",
            flush=True,
        )
        self.restart_at = time.time() + self.delay

    def stop(self, sig=signal.SIGTERM):
        if self.proc and self.proc.poll() is None:
            self.proc.send_signal(sig)

    def wait(self, timeout=10.0):
        if self.proc is None:
            return
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="fdbmonitor")
    ap.add_argument("--conffile", required=True)
    ap.add_argument(
        "--poll-interval", type=float, default=1.0, help="child poll period"
    )
    args = ap.parse_args(argv)

    cp = configparser.ConfigParser()
    read = cp.read(args.conffile)
    if not read:
        ap.error(f"cannot read {args.conffile}")
    general = dict(cp["general"]) if "general" in cp else {}
    restart_delay = float(general.get("restart_delay", 5.0))

    children: list[_Child] = []
    for section in cp.sections():
        if not section.startswith("fdbserver."):
            continue
        name = section.split(".", 1)[1]
        children.append(
            _Child(name, build_args(dict(cp[section]), general), restart_delay)
        )
    if not children:
        ap.error("no [fdbserver.*] sections")

    stopping = []

    def on_signal(signum, _frame):
        stopping.append(signum)

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)

    for c in children:
        c.start()
    try:
        while not stopping:
            time.sleep(args.poll_interval)
            for c in children:
                if stopping:
                    break
                c.poll_and_restart()
    finally:
        print("fdbmonitor: stopping children", flush=True)
        for c in children:
            c.stop()
        for c in children:
            c.wait()
    return 0


if __name__ == "__main__":
    sys.exit(main())
