"""Trace-file consumer: summarize a JSONL trace (plus its rolled
siblings) the way an operator reads the reference's XML traces — event
rates, the loudest SevWarn+ types, and per-role metrics timelines from
the periodic ``*Metrics`` CounterCollection events.

  python -m foundationdb_tpu.tools.trace_analyze trace.jsonl [--top N]

`analyze()` / `format_summary()` are importable so tests and other tools
(the status pipeline's consumers) use the same aggregation the CLI
prints."""

from __future__ import annotations

import json
import os

_META_FIELDS = ("Severity", "Type", "Time", "Machine", "ID", "Elapsed")
_WARN_SEVERITIES = ("Warn", "WarnAlways", "Error")


def load_events(path: str, keep_files: int = 10) -> list[dict]:
    """Events from ``path`` and any rolled siblings (path.N oldest first,
    then the live file) — one roll must not hide the run's history."""
    paths = [
        f"{path}.{i}" for i in range(keep_files, 0, -1) if os.path.exists(f"{path}.{i}")
    ]
    if os.path.exists(path):
        paths.append(path)
    events = []
    for p in paths:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except ValueError:
                    continue  # a roll can truncate the last line
    return events


def analyze(events: list[dict], top: int = 10) -> dict:
    """Aggregate a trace into the operator summary (pure function)."""
    by_type: dict[str, int] = {}
    by_severity: dict[str, int] = {}
    warn_types: dict[str, int] = {}
    times = []
    timelines: dict[str, dict] = {}
    for e in events:
        t = e.get("Type", "?")
        by_type[t] = by_type.get(t, 0) + 1
        sev = str(e.get("Severity", "?"))
        by_severity[sev] = by_severity.get(sev, 0) + 1
        if sev in _WARN_SEVERITIES:
            warn_types[t] = warn_types.get(t, 0) + 1
        when = e.get("Time")
        if isinstance(when, (int, float)):
            times.append(when)
        if t.endswith("Metrics"):
            key = f"{t}#{e.get('ID') or e.get('Machine') or ''}"
            tl = timelines.setdefault(
                key,
                {
                    "points": 0,
                    "first_time": when,
                    "last_time": when,
                    "first": {},
                    "last": {},
                },
            )
            tl["points"] += 1
            tl["last_time"] = when
            for k, v in e.items():
                if k in _META_FIELDS or not isinstance(v, (int, float)):
                    continue
                tl["first"].setdefault(k, v)
                tl["last"][k] = v
    span = (max(times) - min(times)) if len(times) > 1 else 0.0
    top_types = sorted(by_type.items(), key=lambda kv: -kv[1])[:top]
    top_warns = sorted(warn_types.items(), key=lambda kv: -kv[1])[:top]
    return {
        "events": len(events),
        "time_span_seconds": round(span, 3),
        "events_per_second": round(len(events) / span, 2) if span > 0 else None,
        "by_severity": by_severity,
        "top_types": top_types,
        "top_warn_types": top_warns,
        "timelines": timelines,
    }


def format_summary(summary: dict) -> str:
    lines = [
        f"{summary['events']} events over {summary['time_span_seconds']}s"
        + (
            f" ({summary['events_per_second']}/s)"
            if summary["events_per_second"]
            else ""
        ),
        "severity: "
        + ", ".join(f"{k}={v}" for k, v in sorted(summary["by_severity"].items())),
        "",
        "top event types:",
    ]
    for t, n in summary["top_types"]:
        lines.append(f"  {n:8d}  {t}")
    if summary["top_warn_types"]:
        lines.append("")
        lines.append("top SevWarn+ types:")
        for t, n in summary["top_warn_types"]:
            lines.append(f"  {n:8d}  {t}")
    if summary["timelines"]:
        lines.append("")
        lines.append("role metrics timelines (counter deltas first→last):")
        for key, tl in sorted(summary["timelines"].items()):
            deltas = []
            for k, last in tl["last"].items():
                first = tl["first"].get(k, 0)
                if isinstance(last, (int, float)) and last != first:
                    deltas.append(f"{k}+{round(last - first, 3)}")
            span = (tl["last_time"] or 0) - (tl["first_time"] or 0)
            lines.append(
                f"  {key}: {tl['points']} points over {round(span, 1)}s  "
                + (" ".join(deltas[:8]) if deltas else "(no movement)")
            )
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="trace-analyze")
    ap.add_argument("trace", help="JSONL trace file (rolled siblings included)")
    ap.add_argument("--top", type=int, default=10)
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    args = ap.parse_args(argv)
    events = load_events(args.trace)
    summary = analyze(events, top=args.top)
    if args.json:
        print(json.dumps(summary, indent=1, default=str))
    else:
        print(format_summary(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
