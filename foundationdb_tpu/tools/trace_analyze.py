"""Trace-file consumer: summarize a JSONL trace (plus its rolled
siblings) the way an operator reads the reference's XML traces — event
rates, the loudest SevWarn+ types, per-role metrics timelines from the
periodic ``*Metrics`` CounterCollection events — and read the span layer
(runtime/trace.py): per-trace waterfalls and an aggregate critical-path
breakdown ("p50 read = client rpc X ms + storage engine Z ms + ...").

  python -m foundationdb_tpu.tools.trace_analyze trace.jsonl [more.jsonl ...]
      [--top N] [--spans] [--trace TRACE_ID] [--slow-tasks] [--json]

Multiple trace files merge in time order — a TCP cluster writes one file
per fdbserver, and a trace's spans scatter across all of them. Rolled
siblings (path.N) of every file are always included.

`analyze()` / `format_summary()` / `spans_by_trace()` / `critical_path()`
are importable so tests and other tools (the status pipeline's consumers,
perf's bench capture) use the same aggregation the CLI prints."""

from __future__ import annotations

import json
import os

_META_FIELDS = ("Severity", "Type", "Time", "Machine", "ID", "Elapsed")
_WARN_SEVERITIES = ("Warn", "WarnAlways", "Error")


def _read_jsonl(path: str) -> list[dict]:
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except ValueError:
                continue  # a roll can truncate the last line
    return events


def load_events(path, keep_files: int = 10) -> list[dict]:
    """Events from one path or a LIST of paths — each with any rolled
    siblings (path.N oldest first, then the live file) — merged in time
    order. One roll must not hide the run's history, and one process's
    file must not hide the rest of the cluster's: TCP clusters write one
    trace file per fdbserver, so span consumers hand every per-process
    file to one call and get a single timeline back."""
    roots = [path] if isinstance(path, (str, os.PathLike)) else list(path)
    events = []
    for root in roots:
        paths = [
            f"{root}.{i}"
            for i in range(keep_files, 0, -1)
            if os.path.exists(f"{root}.{i}")
        ]
        if os.path.exists(root):
            paths.append(root)
        for p in paths:
            events.extend(_read_jsonl(p))
    if len(roots) > 1:
        # merge across processes: stable sort keeps each file's intra-tick
        # emission order for same-time events
        events.sort(key=lambda e: e.get("Time") or 0.0)
    return events


def analyze(events: list[dict], top: int = 10) -> dict:
    """Aggregate a trace into the operator summary (pure function)."""
    by_type: dict[str, int] = {}
    by_severity: dict[str, int] = {}
    warn_types: dict[str, int] = {}
    times = []
    timelines: dict[str, dict] = {}
    for e in events:
        t = e.get("Type", "?")
        by_type[t] = by_type.get(t, 0) + 1
        sev = str(e.get("Severity", "?"))
        by_severity[sev] = by_severity.get(sev, 0) + 1
        if sev in _WARN_SEVERITIES:
            warn_types[t] = warn_types.get(t, 0) + 1
        when = e.get("Time")
        if isinstance(when, (int, float)):
            times.append(when)
        if t.endswith("Metrics"):
            key = f"{t}#{e.get('ID') or e.get('Machine') or ''}"
            tl = timelines.setdefault(
                key,
                {
                    "points": 0,
                    "first_time": when,
                    "last_time": when,
                    "first": {},
                    "last": {},
                },
            )
            tl["points"] += 1
            tl["last_time"] = when
            for k, v in e.items():
                if k in _META_FIELDS or not isinstance(v, (int, float)):
                    continue
                tl["first"].setdefault(k, v)
                tl["last"][k] = v
    span = (max(times) - min(times)) if len(times) > 1 else 0.0
    top_types = sorted(by_type.items(), key=lambda kv: -kv[1])[:top]
    top_warns = sorted(warn_types.items(), key=lambda kv: -kv[1])[:top]
    return {
        "events": len(events),
        "time_span_seconds": round(span, 3),
        "events_per_second": round(len(events) / span, 2) if span > 0 else None,
        "by_severity": by_severity,
        "top_types": top_types,
        "top_warn_types": top_warns,
        "timelines": timelines,
    }


def format_summary(summary: dict) -> str:
    lines = [
        f"{summary['events']} events over {summary['time_span_seconds']}s"
        + (
            f" ({summary['events_per_second']}/s)"
            if summary["events_per_second"]
            else ""
        ),
        "severity: "
        + ", ".join(f"{k}={v}" for k, v in sorted(summary["by_severity"].items())),
        "",
        "top event types:",
    ]
    for t, n in summary["top_types"]:
        lines.append(f"  {n:8d}  {t}")
    if summary["top_warn_types"]:
        lines.append("")
        lines.append("top SevWarn+ types:")
        for t, n in summary["top_warn_types"]:
            lines.append(f"  {n:8d}  {t}")
    if summary["timelines"]:
        lines.append("")
        lines.append("role metrics timelines (counter deltas first→last):")
        for key, tl in sorted(summary["timelines"].items()):
            deltas = []
            for k, last in tl["last"].items():
                first = tl["first"].get(k, 0)
                if isinstance(last, (int, float)) and last != first:
                    deltas.append(f"{k}+{round(last - first, 3)}")
            span = (tl["last_time"] or 0) - (tl["first_time"] or 0)
            lines.append(
                f"  {key}: {tl['points']} points over {round(span, 1)}s  "
                + (" ".join(deltas[:8]) if deltas else "(no movement)")
            )
    return "\n".join(lines)


# -- timeline mode (metrics history, ISSUE 20) ---------------------------------

_SPARK_CHARS = "▁▂▃▄▅▆▇█"


def sparkline(values: list) -> str:
    """Unicode block sparkline for a numeric series (shared with the
    `cli metrics` renderer)."""
    if not values:
        return ""
    lo, hi = min(values), max(values)
    if hi == lo:
        return _SPARK_CHARS[0] * len(values)
    span = hi - lo
    return "".join(
        _SPARK_CHARS[min(len(_SPARK_CHARS) - 1, int((v - lo) / span * len(_SPARK_CHARS)))]
        for v in values
    )


def timeline_series(events: list[dict], counter: str = None) -> dict:
    """Full per-counter series from the periodic ``*Metrics`` trace
    events: timeline key (``Type#ID``) → {counter: [(t, value)]}. The
    offline twin of the live metrics-history ring — `analyze()` keeps
    only first/last, this keeps every point so --timeline can draw the
    shape between them. ``counter`` filters to one counter name."""
    out: dict[str, dict] = {}
    for e in events:
        t = e.get("Type", "?")
        if not t.endswith("Metrics"):
            continue
        when = e.get("Time")
        if not isinstance(when, (int, float)):
            continue
        key = f"{t}#{e.get('ID') or e.get('Machine') or ''}"
        series = out.setdefault(key, {})
        for k, v in e.items():
            if k in _META_FIELDS or not isinstance(v, (int, float)):
                continue
            if isinstance(v, bool) or (counter and k != counter):
                continue
            series.setdefault(k, []).append((when, v))
    return {k: v for k, v in out.items() if v}


def format_timeline(tls: dict, counter: str = None, width: int = 60) -> str:
    """Sparkline timelines for every (role, counter) series — bounded to
    ``width`` points by tail-keeping (the newest shape is the signal)."""
    if not tls:
        return (
            f"no points for counter {counter!r} in any *Metrics event"
            if counter
            else "no *Metrics events (trace too short, or metrics loops off)"
        )
    lines = []
    for key in sorted(tls):
        series = tls[key]
        lines.append(f"{key}:")
        for name in sorted(series):
            pts = series[name][-width:]
            vals = [v for _t, v in pts]
            lines.append(
                f"  {name:32s} {sparkline(vals)}  "
                f"[{min(vals):g}..{max(vals):g}] last {vals[-1]:g} "
                f"({len(series[name])} pts)"
            )
    return "\n".join(lines)


# -- slow-task mode (run-loop profiler, runtime/profiler.py) -------------------


def slow_tasks(events: list[dict], top: int = 10) -> dict:
    """Aggregate ``Type="SlowTask"`` events (the run-loop profiler's
    blocking-callback attribution) across the merged multi-file timeline:
    per-actor count / total / worst busy time, plus which processes and
    priority bands the stalls hit. The table an operator reads to answer
    "who blocked the loop, where, and for how long"."""
    rows: dict[str, dict] = {}
    total = 0
    for e in events:
        if e.get("Type") != "SlowTask":
            continue
        total += 1
        name = e.get("Actor") or "?"
        r = rows.setdefault(
            name,
            {
                "actor": name,
                "count": 0,
                "total_ms": 0.0,
                "max_ms": 0.0,
                "bands": set(),
                "machines": set(),
            },
        )
        r["count"] += 1
        ms = e.get("BusyMs") or 0.0
        r["total_ms"] += ms
        if ms > r["max_ms"]:
            r["max_ms"] = ms
        if e.get("Band"):
            r["bands"].add(str(e["Band"]))
        if e.get("Machine"):
            r["machines"].add(str(e["Machine"]))
    actors = sorted(rows.values(), key=lambda r: (-r["total_ms"], r["actor"]))[:top]
    return {
        "events": total,
        "actors": [
            {
                "actor": r["actor"],
                "count": r["count"],
                "total_ms": round(r["total_ms"], 3),
                "max_ms": round(r["max_ms"], 3),
                "bands": sorted(r["bands"]),
                "machines": sorted(r["machines"]),
            }
            for r in actors
        ],
    }


def format_slow_tasks(st: dict) -> str:
    if not st["events"]:
        return "no SlowTask events (loop never blocked past RUN_LOOP_SLOW_TASK_MS)"
    lines = [
        f"{st['events']} SlowTask events; top actors by total loop time held:",
        f"{'total ms':>10}  {'max ms':>8}  {'count':>6}  actor [bands] @ machines",
    ]
    for r in st["actors"]:
        lines.append(
            f"{r['total_ms']:10.2f}  {r['max_ms']:8.2f}  {r['count']:6d}  "
            f"{r['actor']} [{','.join(r['bands'])}] @ {','.join(r['machines'])}"
        )
    return "\n".join(lines)


# -- span mode (distributed traces, runtime/trace.py) --------------------------


def spans_by_trace(events: list[dict]) -> dict:
    """trace_id → [span event] (Begin-ordered), merged across processes."""
    out: dict[str, list[dict]] = {}
    for e in events:
        if e.get("Type") == "Span" and e.get("Trace"):
            out.setdefault(e["Trace"], []).append(e)
    for spans in out.values():
        spans.sort(key=lambda s: (s.get("Begin") or 0.0, s.get("SpanId") or ""))
    return out


def _span_children(spans: list[dict]) -> dict:
    kids: dict[str, list[dict]] = {}
    for s in spans:
        kids.setdefault(s.get("Parent") or "", []).append(s)
    return kids


def _roots(spans: list[dict]) -> list[dict]:
    """Spans whose parent is the trace root or isn't in this trace (a hop
    whose file is missing): the waterfall's top level."""
    ids = {s.get("SpanId") for s in spans}
    return [s for s in spans if (s.get("Parent") or "") not in ids]


def format_waterfall(events: list[dict], trace_id: str, width: int = 48) -> str:
    """One trace's spans as an indented waterfall with time bars."""
    spans = spans_by_trace(events).get(trace_id)
    if not spans:
        return f"no spans for trace {trace_id!r}"
    t0 = min(s.get("Begin") or 0.0 for s in spans)
    t1 = max((s.get("Begin") or 0.0) + (s.get("Dur") or 0.0) for s in spans)
    total = max(t1 - t0, 1e-9)
    kids = _span_children(spans)
    lines = [f"trace {trace_id}: {total * 1000:.3f} ms, {len(spans)} spans"]

    def render(s, depth):
        b = (s.get("Begin") or 0.0) - t0
        d = s.get("Dur") or 0.0
        lo = int(b / total * width)
        hi = max(lo + 1, int((b + d) / total * width))
        bar = " " * lo + "█" * (hi - lo)
        lines.append(
            f"  +{b * 1000:8.3f} ms {d * 1000:8.3f} ms "
            f"|{bar:<{width}}| "
            + "  " * depth
            + f"{s.get('Name', '?')} @ {s.get('Machine', '')}"
        )
        for c in kids.get(s.get("SpanId"), []):
            render(c, depth + 1)

    for r in _roots(spans):
        render(r, 0)
    return "\n".join(lines)


def _interval_union(ivs: list) -> float:
    """Total length covered by a set of (begin, end) intervals."""
    total, cur_b, cur_e = 0.0, None, None
    for b, e in sorted(ivs):
        if e <= b:
            continue
        if cur_e is None or b > cur_e:
            if cur_e is not None:
                total += cur_e - cur_b
            cur_b, cur_e = b, e
        else:
            cur_e = max(cur_e, e)
    if cur_e is not None:
        total += cur_e - cur_b
    return total


def critical_path(events: list[dict], root_prefix: str = "") -> dict:
    """Aggregate stage attribution across traces: for each root-span name
    (optionally filtered by prefix, e.g. "Client."), the p50/mean total
    and the mean SELF time of every span name under it. Self time is a
    span's duration minus the UNION of its children's intervals (clipped
    to the span) — concurrent children and both sides of an RPC hop
    (e.g. the proxy's resolve stage and the resolver's own span cover the
    same wall time) are counted once, so per trace the stages sum to the
    root duration and named stages account for the whole measured
    latency, unattributed wire/queue time landing in each parent's self
    time."""
    by_trace = spans_by_trace(events)
    per_root: dict[str, dict] = {}
    for spans in by_trace.values():
        ids = {s.get("SpanId"): s for s in spans}
        kids = _span_children(spans)

        def self_times(root, acc):
            stack = [root]
            while stack:
                s = stack.pop()
                cs = kids.get(s.get("SpanId"), [])
                b = s.get("Begin") or 0.0
                d = s.get("Dur") or 0.0
                covered = _interval_union(
                    [
                        (
                            max(b, c.get("Begin") or 0.0),
                            min(b + d, (c.get("Begin") or 0.0) + (c.get("Dur") or 0.0)),
                        )
                        for c in cs
                    ]
                )
                name = s.get("Name", "?")
                acc[name] = acc.get(name, 0.0) + max(0.0, d - covered)
                stack.extend(cs)

        for r in _roots(spans):
            name = r.get("Name", "?")
            if root_prefix and not name.startswith(root_prefix):
                continue
            agg = per_root.setdefault(name, {"totals": [], "stages": {}})
            agg["totals"].append(r.get("Dur") or 0.0)
            acc: dict[str, float] = {}
            self_times(r, acc)
            for st, t in acc.items():
                agg["stages"][st] = agg["stages"].get(st, 0.0) + t

    out = {}
    for name, agg in per_root.items():
        totals = sorted(agg["totals"])
        n = len(totals)
        mean = sum(totals) / n
        stages = [
            {
                "stage": st,
                "mean_ms": round(t / n * 1000, 4),
                "share": round((t / n) / mean, 4) if mean > 0 else 0.0,
            }
            for st, t in sorted(agg["stages"].items(), key=lambda kv: -kv[1])
        ]
        out[name] = {
            "traces": n,
            "p50_ms": round(totals[n // 2] * 1000, 4),
            # nearest-rank p99 — the tail number watch-latency SLOs cite
            "p99_ms": round(totals[min(n - 1, (n * 99) // 100)] * 1000, 4),
            "mean_ms": round(mean * 1000, 4),
            "stages": stages,
            # named-stage coverage of the mean (== 1.0 by construction
            # when every span nests; <1 flags spans lost to missing files)
            "coverage": round(
                sum(s["mean_ms"] for s in stages) / (mean * 1000), 4
            )
            if mean > 0
            else 0.0,
        }
    return out


def format_critical_path(cp: dict) -> str:
    if not cp:
        return "no sampled spans (set TRACE_SAMPLE_RATE or a debug id)"
    lines = []
    for name, agg in sorted(cp.items()):
        lines.append(
            f"{name}: p50 {agg['p50_ms']:.3f} ms / p99 {agg['p99_ms']:.3f} ms "
            f"over {agg['traces']} traces "
            f"(stage coverage {agg['coverage']:.0%})"
        )
        for s in agg["stages"]:
            lines.append(
                f"    {s['mean_ms']:9.3f} ms  {s['share']:6.1%}  {s['stage']}"
            )
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="trace-analyze")
    ap.add_argument(
        "trace",
        nargs="+",
        help="JSONL trace file(s) — per-process files merge; rolled "
        "siblings always included",
    )
    ap.add_argument("--top", type=int, default=10)
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument(
        "--spans",
        action="store_true",
        help="span mode: critical-path breakdown (and waterfalls via --trace)",
    )
    ap.add_argument("--trace-id", default=None, help="render one trace's waterfall")
    ap.add_argument(
        "--slow-tasks",
        action="store_true",
        dest="slow_tasks",
        help="top-N table of SlowTask events (run-loop blocking attribution)",
    )
    ap.add_argument(
        "--timeline",
        action="store_true",
        help="sparkline timelines of the periodic *Metrics counters "
        "(every point, not just first→last deltas)",
    )
    ap.add_argument(
        "--counter",
        default=None,
        help="with --timeline: restrict to one counter name",
    )
    args = ap.parse_args(argv)
    events = load_events(args.trace)
    if args.timeline:
        tls = timeline_series(events, counter=args.counter)
        if args.json:
            print(json.dumps(tls, indent=1, default=str))
        else:
            print(format_timeline(tls, counter=args.counter))
        return 0
    if args.trace_id:
        print(format_waterfall(events, args.trace_id))
        return 0
    if args.slow_tasks:
        st = slow_tasks(events, top=args.top)
        if args.json:
            print(json.dumps(st, indent=1, default=str))
        else:
            print(format_slow_tasks(st))
        return 0
    if args.spans:
        cp = critical_path(events)
        if args.json:
            print(json.dumps(cp, indent=1, default=str))
        else:
            print(format_critical_path(cp))
        return 0
    summary = analyze(events, top=args.top)
    if args.json:
        print(json.dumps(summary, indent=1, default=str))
    else:
        print(format_summary(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
