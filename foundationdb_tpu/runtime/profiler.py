"""Run-loop profiler: slow-task attribution, per-priority starvation, and
sampled flame evidence.

The analog of the reference's run-loop profiler + NetworkMetrics
(flow/Net2.actor.cpp's checkForSlowTask / NetworkMetrics in flow/network.h,
flow/Profiler.actor.cpp's sampling profiler): the whole process is one
single-threaded priority loop, so one blocking callback stalls every role
hosted by the process. Spans (runtime/trace.py) measure wall time *between*
hops and counters (runtime/stats.py) measure *what* happened; this module
attributes **on-CPU time holding the loop** — who ran, for how long, at what
priority, and who waited because of it.

Three instruments, one per failure mode:

- ``LoopProfiler`` — wraps every callback the loop executes (both
  personalities hook it from ``EventLoop.run`` / ``RealLoop.run``).
  Each callback is attributed to its owning actor (``futures.Task`` threads
  the coroutine's ``__qualname__`` through the scheduling calls), rolled up
  per actor (steps, busy seconds, max single step) and per priority band
  (busy fraction, schedule→run starvation latency as a ``LatencySample``).
  On the REAL personality a callback that holds the loop longer than
  ``RUN_LOOP_SLOW_TASK_MS`` emits a ``Type="SlowTask"`` trace event (the
  reference's SlowTask / Net2SlowTaskTrace) naming the actor. The SIM
  personality emits no wall-dependent trace events — its step counters are
  deterministic under a fixed seed, so attribution is *testable*.

- per-band ``NetworkMetrics``: the profiler owns a ``CounterCollection``
  (``RunLoopMetrics`` periodic trace events) with step/slow-task counters,
  per-band starvation samples, select/poll latency on the real loop, and a
  queue-depth gauge — everything in the collection is loop-derived, so the
  sim's periodic RunLoopMetrics events stay byte-deterministic.

- ``FlameProfiler`` — a sampler THREAD reading the loop thread's stack via
  ``sys._current_frames()`` at ``PROFILER_SAMPLE_HZ``, aggregating collapsed
  stacks into flamegraph/speedscope-compatible folded lines
  (``a;b;c 42``) — the evidence for *where inside the callback* the time
  went, dumped via ``cli profile``.

Wall-clock reads here are the profiler's measurement function, not sim
state: nothing measured feeds back into scheduling, so replays stay
bit-identical (the inline flowlint disables below mark each deliberate
site).
"""

from __future__ import annotations

import sys
import threading
from typing import Optional

from .loop import TaskPriority
from .stats import CounterCollection

# priority → band: the reference's ~40-level TaskPriority enum collapsed to
# the levels this system schedules at (loop.py TaskPriority). A priority
# lands in the highest band whose threshold it reaches; IO readiness
# callbacks (no priority — the selector dispatches them directly) get the
# dedicated "io" band.
PRIORITY_BANDS = (
    (TaskPriority.MAX, "max"),
    (TaskPriority.COORDINATION, "coordination"),
    (TaskPriority.RESOLVER, "resolver"),
    (TaskPriority.TLOG_COMMIT, "tlog_commit"),
    (TaskPriority.PROXY_COMMIT, "proxy_commit"),
    (TaskPriority.DEFAULT, "default"),
    (TaskPriority.STORAGE, "storage"),
    (TaskPriority.LOW, "low"),
    (TaskPriority.ZERO, "zero"),
)

BAND_ORDER = tuple(name for _thresh, name in PRIORITY_BANDS) + ("io",)


def band_of(priority: int) -> str:
    for thresh, name in PRIORITY_BANDS:
        if priority >= thresh:
            return name
    return "zero"


class _ActorStats:
    __slots__ = ("name", "steps", "busy", "max_busy")

    def __init__(self, name: str):
        self.name = name
        self.steps = 0
        self.busy = 0.0
        self.max_busy = 0.0


class LoopProfiler:
    """Per-loop callback attribution. Installed as ``loop.profiler`` by the
    world constructors (net/sim.py Sim, net/tcp.py RealWorld) behind the
    ``RUN_LOOP_PROFILER`` knob; the loops call ``run_task``/``run_io``/
    ``select_done`` on the hot path, everything else is pull-only."""

    def __init__(self, loop, knobs=None, wall: bool = True, ident: str = ""):
        import time as _time

        self.loop = loop
        self.wall = wall  # True = real personality: SlowTask trace events
        self.ident = ident
        # distinguishes distinct loops behind identical per-process
        # snapshots (every sim process shares ONE loop; status consumers
        # dedupe on this before summing)
        self.loop_id = f"loop-{id(loop):x}"
        self._slow_s = (
            getattr(knobs, "RUN_LOOP_SLOW_TASK_MS", 50.0) or 50.0
        ) / 1000.0
        self._sample_hz = getattr(knobs, "PROFILER_SAMPLE_HZ", 100.0)
        # measurement clock: a REFERENCE latched once (dependency-injection
        # shape); measured durations never feed back into scheduling
        self._clock = _time.perf_counter  # flowlint: disable=det-wall-clock
        self._t_start = self._clock()
        self._busy_total = 0.0
        self.actors: dict[str, _ActorStats] = {}
        # the worker-level CounterCollection behind process.metrics: only
        # loop-derived values live in it, so the periodic RunLoopMetrics
        # trace events are byte-deterministic on the sim personality
        self.stats = CounterCollection("RunLoop", ident)
        self._c_steps = self.stats.counter("steps")
        self._c_slow = self.stats.counter("slowTasks")
        self._c_io = self.stats.counter("ioCallbacks")
        self._c_selects = self.stats.counter("selects")
        self.stats.gauge("queueDepth", lambda: len(loop._queue))
        self._sel_sample = self.stats.latency("selectSeconds")
        # band name → [busy_seconds, steps, starvation LatencySample]
        self._bands: dict[str, list] = {}
        for name in BAND_ORDER:
            self._bands[name] = [
                0.0,
                0,
                self.stats.latency(f"starvation_{name}"),
            ]
        self._band_cache: dict[int, list] = {}  # priority → band record
        self._band_names: dict[int, str] = {}
        self.flame: Optional[FlameProfiler] = None
        self._trace_loop_claimed = False

    # -- hot path (called by the loops around every callback) ------------------

    def run_task(self, fn, owner: Optional[str], priority: int, lag: float) -> None:
        """Execute one queued callback under attribution. ``owner`` is the
        scheduling actor's qualname (None for plain timers/posted work);
        ``lag`` is schedule→run latency — virtual on the sim loop (where it
        is deterministically ~0: virtual time warps straight to the due
        time), wall on the real loop (genuine starvation)."""
        band = self._band_cache.get(priority)
        if band is None:
            name = band_of(priority)
            band = self._band_cache[priority] = self._bands[name]
            self._band_names[priority] = name
        band[1] += 1
        band[2].add(lag)
        self._c_steps.value += 1
        clock = self._clock
        t0 = clock()
        try:
            fn()
        finally:
            busy = clock() - t0
            self._busy_total += busy
            band[0] += busy
            name = owner or getattr(fn, "__qualname__", "") or "callback"
            a = self.actors.get(name)
            if a is None:
                a = self.actors[name] = _ActorStats(name)
            a.steps += 1
            a.busy += busy
            if busy > a.max_busy:
                a.max_busy = busy
            if busy >= self._slow_s and self.wall:
                self._slow_task(name, busy, priority)

    def run_io(self, cb) -> None:
        """One selector-readiness callback (real personality only)."""
        band = self._bands["io"]
        self._c_io.value += 1
        clock = self._clock
        t0 = clock()
        try:
            cb()
        finally:
            busy = clock() - t0
            self._busy_total += busy
            band[0] += busy
            band[1] += 1
            name = getattr(cb, "__qualname__", "") or "io"
            a = self.actors.get(name)
            if a is None:
                a = self.actors[name] = _ActorStats(name)
            a.steps += 1
            a.busy += busy
            if busy > a.max_busy:
                a.max_busy = busy
            if busy >= self._slow_s and self.wall:
                self._slow_task(name, busy, -1)

    def select_done(self, dt: float) -> None:
        """One select()/poll() block on the real loop."""
        self._c_selects.value += 1
        self._sel_sample.add(dt)

    def _slow_task(self, name: str, busy: float, priority: int) -> None:
        from .trace import SevWarn, trace

        self._c_slow.value += 1
        trace(
            SevWarn,
            "SlowTask",
            self.ident,
            Actor=name,
            BusyMs=round(busy * 1000.0, 3),
            Priority=priority,
            Band="io" if priority < 0 else self._band_names.get(
                priority, band_of(priority)
            ),
        )

    # -- snapshots --------------------------------------------------------------

    def busy_fraction(self) -> float:
        """Lifetime on-CPU fraction of this loop (wall-measured)."""
        return self._busy_total / max(self._clock() - self._t_start, 1e-9)

    def snapshot(self, top: int = 10) -> dict:
        """The ``run_loop`` section (process.metrics endpoint / status):
        loop totals, per-band busy fraction + starvation percentiles, and
        the hottest actors by on-CPU time. Wall fields (busy/elapsed) are
        evidence, not sim state — only the step counters are deterministic
        on the sim personality."""
        elapsed = max(
            self._clock() - self._t_start, 1e-9
        )
        bands = {}
        for name in BAND_ORDER:
            busy, steps, sample = self._bands[name]
            bands[name] = {
                "steps": steps,
                "busy_seconds": round(busy, 6),
                "busy_fraction": round(busy / elapsed, 6),
                "starvation": sample.snapshot(),
            }
        hot = sorted(
            self.actors.values(), key=lambda a: (-a.busy, -a.steps, a.name)
        )[:top]
        return {
            "loop_id": self.loop_id,
            "personality": "real" if self.wall else "sim",
            "steps": self._c_steps.value,
            "io_callbacks": self._c_io.value,
            "slow_tasks": self._c_slow.value,
            "busy_seconds": round(self._busy_total, 6),
            "elapsed_seconds": round(elapsed, 3),
            "busy_fraction": round(self._busy_total / elapsed, 6),
            "queue_depth": len(self.loop._queue),
            "select_seconds": self._sel_sample.snapshot(),
            "bands": bands,
            "hot_actors": [
                {
                    "name": a.name,
                    "steps": a.steps,
                    "busy_seconds": round(a.busy, 6),
                    "max_ms": round(a.max_busy * 1000.0, 3),
                }
                for a in hot
            ],
        }

    async def ensure_trace_loop(self, interval: float, process: str):
        """Periodic RunLoopMetrics trace events — claimed by the FIRST
        worker on the loop (every sim process shares one loop; two trace
        loops would fight over the counters' interval state)."""
        if self._trace_loop_claimed:
            return
        self._trace_loop_claimed = True
        await self.stats.trace_loop(interval, process)

    # -- flame sampling ---------------------------------------------------------

    def flame_start(self, hz: Optional[float] = None) -> "FlameProfiler":
        """Start (or restart) sampling the CALLING thread's stack — the
        loop thread, since only loop code calls this."""
        if self.flame is not None:
            self.flame.stop()
        self.flame = FlameProfiler(hz or self._sample_hz)
        self.flame.start()
        return self.flame

    def flame_stop(self) -> str:
        """Stop the sampler and return the folded stacks collected."""
        if self.flame is None:
            return ""
        folded = self.flame.stop()
        self.flame = None
        return folded


class FlameProfiler:
    """Sampling stack profiler for the loop thread (the analog of
    flow/Profiler.actor.cpp's SIGPROF sampler, portable via a daemon
    thread + ``sys._current_frames``). Output is folded-stack lines
    (``file:func;file:func;... count``) consumable by flamegraph.pl and
    speedscope. The sampler never touches loop state — it only *reads*
    frames, so it is safe to run against either personality."""

    def __init__(self, hz: float = 100.0, thread_id: Optional[int] = None):
        self.hz = max(float(hz), 1.0)
        self.thread_id = thread_id if thread_id is not None else threading.get_ident()
        self.samples = 0
        self._counts: dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="flame-sampler", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        interval = 1.0 / self.hz
        counts = self._counts
        # Event.wait paces the sampler on ITS OWN thread; the loop thread
        # never blocks on the sampler
        while not self._stop.wait(interval):
            frame = sys._current_frames().get(self.thread_id)
            if frame is None:
                continue
            stack = []
            while frame is not None:
                code = frame.f_code
                fname = code.co_filename
                cut = fname.rfind("/")
                stack.append(f"{fname[cut + 1:]}:{code.co_name}")
                frame = frame.f_back
            key = ";".join(reversed(stack))
            counts[key] = counts.get(key, 0) + 1
            self.samples += 1

    def stop(self) -> str:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=2.0)
            self._thread = None
        return self.folded()

    def folded(self) -> str:
        """Collapsed-stack lines, hottest first — flamegraph.pl /
        speedscope input format."""
        return "\n".join(
            f"{stack} {n}"
            for stack, n in sorted(
                self._counts.items(), key=lambda kv: (-kv[1], kv[0])
            )
        )


def install(loop, knobs=None, wall: bool = True, ident: str = "") -> Optional[LoopProfiler]:
    """Attach a LoopProfiler to ``loop`` if the knob allows and none is
    installed yet (several RealWorlds may share one loop — first wins, and
    a world must never displace a profiler that has been accumulating)."""
    if knobs is not None and not getattr(knobs, "RUN_LOOP_PROFILER", True):
        return getattr(loop, "profiler", None)
    if getattr(loop, "profiler", None) is None:
        loop.profiler = LoopProfiler(loop, knobs=knobs, wall=wall, ident=ident)
    return loop.profiler
