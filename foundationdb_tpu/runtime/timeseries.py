"""Bounded metrics history (ISSUE 20): a ring buffer of CounterCollection
snapshots, so "what did this counter look like 60s ago" has an answer
without replaying a trace file.

The reference keeps its per-role counters only as periodic trace events;
operators reconstruct timelines offline (contrib's monitoring pollers).
Here every CounterCollection can own a MetricsHistory that a host loop
(`CounterCollection.history_loop`) feeds at a knob-set cadence
(METRICS_HISTORY_INTERVAL / METRICS_HISTORY_SAMPLES); the worker's
`worker.metricsHistory` endpoint, `cli metrics <role> <counter>` and
`tools/trace_analyze --timeline` read it back.

Only numeric scalars are retained — gauge lists and latency/band dicts
are dropped at record time so a full ring stays a few KB per role. Time
is always passed IN (the sim's model clock or the real loop's), never
read here: the module stays flowlint-deterministic by construction.
"""

from __future__ import annotations

from collections import deque


class MetricsHistory:
    """Fixed-capacity ring of ``(t, {name: value})`` snapshots."""

    __slots__ = ("capacity", "_buf")

    def __init__(self, capacity: int):
        self.capacity = max(1, int(capacity))
        self._buf: deque = deque(maxlen=self.capacity)

    def __len__(self) -> int:
        return len(self._buf)

    def record(self, t: float, snapshot: dict) -> None:
        """Append one snapshot, keeping only numeric scalar fields (bools
        excluded: they are flags, not series)."""
        vals = {
            k: v
            for k, v in snapshot.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }
        self._buf.append((t, vals))

    def points(self) -> list:
        """[(t, {name: value})] oldest → newest."""
        return list(self._buf)

    def series(self, name: str) -> list:
        """[(t, value)] for one counter, skipping snapshots without it."""
        return [(t, vals[name]) for t, vals in self._buf if name in vals]

    def names(self) -> list:
        """Every counter name seen anywhere in the ring (sorted)."""
        seen: set = set()
        for _t, vals in self._buf:
            seen.update(vals)
        return sorted(seen)

    def to_dict(self) -> dict:
        """Wire/JSON shape for the `*.metricsHistory` endpoints."""
        return {
            "capacity": self.capacity,
            "points": [[t, dict(vals)] for t, vals in self._buf],
        }

    @staticmethod
    def from_dict(d: dict) -> "MetricsHistory":
        h = MetricsHistory(d.get("capacity") or 1)
        for t, vals in d.get("points") or []:
            h._buf.append((t, dict(vals)))
        return h
