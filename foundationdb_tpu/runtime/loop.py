"""Deterministic single-threaded event loop with virtual time.

The analog of the reference's Net2 run loop (flow/Net2.actor.cpp:545) and —
more importantly — of the Sim2 deterministic simulator
(fdbrpc/sim2.actor.cpp:720): all scheduling, timers, and randomness flow
through one seeded loop, so any execution is exactly reproducible from its
seed. Time is virtual: ``now()`` advances only when the loop runs a timer,
never with wall-clock (the property that makes whole-cluster simulation of
hours of activity run in seconds and replays bit-identical).

Tasks carry priorities (the reference's ~40-level TaskPriority enum,
flow/network.h:30-75, collapsed to the levels this system uses); ready tasks
at the same time run in (priority, seq) order, with seq assigned at schedule
time — deterministic FIFO within a priority.
"""

from __future__ import annotations

import heapq
from typing import Callable, Optional

from .rng import DeterministicRandom


class TaskPriority:
    MAX = 1000000
    COORDINATION = 8800
    TLOG_COMMIT = 8570
    PROXY_COMMIT = 8540
    RESOLVER = 8700
    DEFAULT = 7500
    STORAGE = 6500
    LOW = 2000
    ZERO = 0


class Cancelled(Exception):
    """Raised inside an actor when its future is cancelled (the analog of
    actor_cancelled, flow/error_definitions.h)."""


# owner sentinel marking a batch-runner queue entry: the run loops execute
# it UNWRAPPED (the runner applies per-item profiler attribution itself —
# wrapping it again would double-count busy time and steps)
BATCH_OWNER = "<batch>"


class EventLoop:
    """Priority run loop over virtual time. Single-threaded; determinism
    comes from (time, -priority, seq) ordering and the seeded RNG."""

    def __init__(self, seed: int = 0):
        self._queue: list[tuple] = []  # (when, -priority, seq, fn, owner)
        self._time = 0.0
        self._seq = 0
        self.random = DeterministicRandom(seed)
        self.stopped = False
        self._stall_detector: Optional[Callable[[], None]] = None
        # run-loop profiler (runtime/profiler.py), installed by the world
        # constructors behind the RUN_LOOP_PROFILER knob; when present,
        # every callback executes under per-actor/per-band attribution
        self.profiler = None
        # settle-slab hook (futures.settle_batch): while non-None, Task
        # wakeups append (task, value, error) here instead of paying one
        # call_soon per woken task; the installer flushes the slab as
        # per-priority call_soon_batch entries
        self._wake_collector = None

    def now(self) -> float:
        return self._time

    def call_at(
        self,
        when: float,
        fn: Callable[[], None],
        priority: int = TaskPriority.DEFAULT,
        owner: Optional[str] = None,
    ) -> None:
        # reentrancy-safe: a GC run triggered by the allocations below can
        # finalize coroutines whose finally-blocks schedule more callbacks
        # (re-entering this method); the seq must be latched in a local or
        # two entries can share one and the heap falls over comparing the
        # callables
        seq = self._seq = self._seq + 1
        heapq.heappush(
            self._queue, (max(when, self._time), -priority, seq, fn, owner)
        )

    def call_soon(
        self,
        fn: Callable[[], None],
        priority: int = TaskPriority.DEFAULT,
        owner: Optional[str] = None,
    ) -> None:
        self.call_at(self._time, fn, priority, owner)

    def call_soon_batch(
        self, items: list, priority: int = TaskPriority.DEFAULT
    ) -> None:
        """Schedule many callbacks as ONE queue entry: ``items`` is a list
        of ``(fn, owner)`` pairs run in order within a single loop step —
        the server-side batch dispatch that drains a whole super-frame of
        requests per wakeup instead of paying a heap entry (and a
        profiler-wrapped step) per request. Each item still executes under
        its own per-actor attribution; only the schedule→run lag collapses
        to the batch's (it is one schedule)."""
        if len(items) == 1:
            fn, owner = items[0]
            self.call_at(self._time, fn, priority, owner)
            return

        def _run_batch():
            prof = self.profiler
            if prof is None:
                for fn, _owner in items:
                    fn()
            else:
                for fn, owner in items:
                    prof.run_task(fn, owner, priority, 0.0)

        self.call_at(self._time, _run_batch, priority, BATCH_OWNER)

    def run(self, until: float = float("inf"), stop_when: Callable[[], bool] = None):
        """Drain tasks until the queue empties, virtual time passes ``until``,
        or ``stop_when()`` turns true."""
        while self._queue and not self.stopped:
            when, negpri, _seq, fn, owner = self._queue[0]
            if when > until:
                break
            heapq.heappop(self._queue)
            self._time = max(self._time, when)
            prof = self.profiler
            if prof is None or owner is BATCH_OWNER:
                fn()
            else:
                # virtual schedule→run lag: deterministically ~0 here (the
                # sim warps time to the due instant), but the call keeps
                # one code path for both personalities
                prof.run_task(fn, owner, -negpri, self._time - when)
            if stop_when is not None and stop_when():
                break
        return self._time


class RealLoop(EventLoop):
    """Wall-clock run loop with socket IO — the non-simulated personality
    of the event loop (the reference's Net2 over boost.asio,
    flow/Net2.actor.cpp:545 + AsioReactor: timers and socket readiness in
    one reactor). The actor/future machinery is loop-agnostic, so server
    code runs unmodified on either personality; only this loop may block
    in ``select``.
    """

    def __init__(self, seed: Optional[int] = None):
        import os as _os
        import selectors
        from collections import deque

        if seed is None:
            # the REAL personality seeds from OS entropy by design: there is
            # no replay to protect, and distinct processes must diverge
            seed = int.from_bytes(_os.urandom(8), "little")  # flowlint: disable=det-entropy
        super().__init__(seed)
        self._selector = selectors.DefaultSelector()
        self._t0 = self._monotonic()
        self._time = 0.0
        # cross-thread handoff: worker threads (device waits, blocking IO)
        # may not touch the heap; they append here and the loop drains at
        # the top of each cycle (the select timeout bounds wakeup latency)
        self._posted = deque()
        # external work in flight (e.g. a resolver's device thread): the
        # loop must not take the "nothing left to wait for" exit while a
        # completion post is still coming. Both counters are mutated ONLY
        # on the loop thread (begin at submit, end inside the posted
        # completion), so no lock is needed.
        self._external_pending = 0
        # self-pipe: post() writes a byte so a loop parked in select()
        # wakes immediately instead of at the 50 ms timeout (the reference
        # wakes its reactor the same way, Net2's ASIOReactor::wake)
        import socket as _socket

        self._wake_r, self._wake_w = _socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self.add_reader(self._wake_r, self._drain_wake)

    def _drain_wake(self) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, OSError):
            pass

    def close(self) -> None:
        """Release the wake pipe AND the selector (a loop is one-per-process
        in production, but tests create many — an unclosed selector leaks
        one epoll fd per loop until the fd table fills). Idempotent: the
        __del__ backstop and explicit close may both run."""
        if self.profiler is not None:
            try:
                self.profiler.flame_stop()  # sampler thread must not outlive us
            except Exception:
                pass
        try:
            self.remove_reader(self._wake_r)
        except Exception:
            pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass
        try:
            self._selector.close()
        except (OSError, RuntimeError):
            pass

    def __del__(self):  # backstop for leak-prone test loops
        self.close()

    def post(self, fn: Callable[[], None]) -> None:
        """Schedule ``fn`` onto the loop from ANY thread (deque.append is
        atomic). The reference's onMainThread (flow/ThreadHelper.actor.h)."""
        self._posted.append(fn)
        try:
            self._wake_w.send(b"\x00")
        except (BlockingIOError, OSError):
            pass  # pipe full = wakeup already pending

    def external_begin(self) -> None:
        self._external_pending += 1

    def external_end(self) -> None:
        self._external_pending -= 1

    @staticmethod
    def _monotonic() -> float:
        import time as _time

        # the ONE place wall time enters the system: RealLoop IS the
        # wall-clock personality; everything above it sees loop.now()
        return _time.monotonic()  # flowlint: disable=det-wall-clock

    def _wall(self) -> float:
        return self._monotonic() - self._t0

    # -- IO registration -------------------------------------------------------

    def add_reader(self, sock, cb: Callable[[], None]) -> None:
        import selectors

        try:
            key = self._selector.get_key(sock)
        except KeyError:
            self._selector.register(sock, selectors.EVENT_READ, [cb, None])
            return
        key.data[0] = cb
        self._selector.modify(sock, key.events | selectors.EVENT_READ, key.data)

    def add_writer(self, sock, cb: Callable[[], None]) -> None:
        import selectors

        try:
            key = self._selector.get_key(sock)
        except KeyError:
            self._selector.register(sock, selectors.EVENT_WRITE, [None, cb])
            return
        key.data[1] = cb
        self._selector.modify(sock, key.events | selectors.EVENT_WRITE, key.data)

    def remove_reader(self, sock) -> None:
        self._remove(sock, 0)

    def remove_writer(self, sock) -> None:
        self._remove(sock, 1)

    def _remove(self, sock, slot: int) -> None:
        import selectors

        try:
            key = self._selector.get_key(sock)
        except (KeyError, ValueError):
            return  # never registered, or already closed (fd -1)
        key.data[slot] = None
        events = (selectors.EVENT_READ if key.data[0] else 0) | (
            selectors.EVENT_WRITE if key.data[1] else 0
        )
        if events:
            self._selector.modify(sock, events, key.data)
        else:
            self._selector.unregister(sock)

    # -- running ---------------------------------------------------------------

    def run(self, until: float = float("inf"), stop_when: Callable[[], bool] = None):
        import selectors

        while not self.stopped:
            prof = self.profiler
            while self._posted:
                self.call_soon(self._posted.popleft())
            self._time = self._wall()
            # drain due callbacks
            while self._queue and self._queue[0][0] <= self._time:
                when, negpri, _s, fn, owner = heapq.heappop(self._queue)
                if prof is None or owner is BATCH_OWNER:
                    fn()
                else:
                    # wall schedule→run lag: how long past due this task
                    # ran — the starvation the blocked loop inflicted
                    prof.run_task(fn, owner, -negpri, self._time - when)
                if stop_when is not None and stop_when():
                    return self._time
                self._time = self._wall()
            if stop_when is not None and stop_when():
                return self._time
            if self._time >= until:
                return self._time
            if (
                not self._queue
                and len(self._selector.get_map()) <= 1  # wake pipe only
                and self._external_pending == 0
                and not self._posted
            ):
                return self._time  # nothing left to wait for
            wait = 0.05
            if self._queue:
                wait = max(0.0, min(wait, self._queue[0][0] - self._time))
            if until != float("inf"):
                wait = max(0.0, min(wait, until - self._time))
            if prof is None:
                ready = self._selector.select(wait)
            else:
                t0 = self._monotonic()
                ready = self._selector.select(wait)
                prof.select_done(self._monotonic() - t0)
            for key, events in ready:
                rd, wr = key.data
                if events & selectors.EVENT_READ and rd is not None:
                    rd() if prof is None else prof.run_io(rd)
                if events & selectors.EVENT_WRITE and wr is not None:
                    wr() if prof is None else prof.run_io(wr)
            # a stop condition satisfied inside an IO callback must end the
            # run NOW — falling through to the next cycle would execute
            # whatever timers are due (and, with an empty selector map,
            # could park in select again) before anyone re-consulted it
            if stop_when is not None and stop_when():
                return self._time
        return self._time


_current: Optional[EventLoop] = None


def current_loop() -> EventLoop:
    if _current is None:
        raise RuntimeError("no event loop active (use with_loop / Sim)")
    return _current


def set_loop(loop: Optional[EventLoop]) -> None:
    global _current
    _current = loop


def now() -> float:
    return current_loop().now()
