"""Structured trace events — the analog of flow/Trace.cpp TraceEvent.

Events are dicts with severity, type, time, and process identity, collected
per run (and optionally mirrored to a JSONL file, the counterpart of the
reference's rolling XML trace logs — like the reference, the file rolls at
a size threshold, keeping a bounded set of numbered predecessors). A
SevError event marks the run failed — exactly the simulator's pass/fail
criterion (SURVEY.md §3.4).

Distributed spans (the analog of flow/Tracing.h Span/SpanContext) live
here too: a ``Span`` is a timed interval inside one trace, emitted as a
``Type="Span"`` event when it finishes, so spans share the TraceLog's
JSONL files, rolling, and consumers. The ambient *active* span context is
carried per-actor by the futures machinery (runtime/futures.py saves and
restores it around every actor step, so it survives awaits and is
inherited at spawn) and across RPCs by the network envelopes (net/sim.py,
net/tcp.py) — servers inherit the caller's context without any request
dataclass knowing about tracing. Unsampled traces cost one None check:
``span()`` returns the shared no-op span unless an ancestor was sampled.

Determinism: span ids count up per event loop (not per process image), and
sampling decisions draw from seeded RNGs, so two same-seed sim runs emit
byte-identical span sets.
"""

from __future__ import annotations

import json
import os
from typing import Optional

SevDebug, SevInfo, SevWarn, SevWarnAlways, SevError = 5, 10, 20, 30, 40

_SEV_NAMES = {5: "Debug", 10: "Info", 20: "Warn", 30: "WarnAlways", 40: "Error"}

# the reference rolls trace files at 10 MB (TraceLog's maxLogsSize /
# rollsize, flow/Trace.cpp) and prunes old ones; same defaults here —
# overridable per-log (tools pass Knobs.TRACE_ROLL_BYTES / _KEEP)
DEFAULT_ROLL_BYTES = 10 << 20
DEFAULT_ROLL_KEEP = 10


class TraceLog:
    def __init__(
        self,
        path: Optional[str] = None,
        min_severity: int = SevInfo,
        max_file_bytes: int = DEFAULT_ROLL_BYTES,
        keep_files: int = DEFAULT_ROLL_KEEP,
    ):
        self.events: list[dict] = []
        self.error_count = 0
        self.min_severity = min_severity
        self.path = path
        self.max_file_bytes = max_file_bytes
        self.keep_files = max(1, keep_files)
        self.rolls = 0
        self._file = open(path, "a") if path else None
        self._file_bytes = os.path.getsize(path) if path else 0

    def log(self, severity: int, event_type: str, time: float, process: str, **fields):
        if severity < self.min_severity:
            return
        ev = {
            "Severity": _SEV_NAMES.get(severity, severity),
            "Type": event_type,
            "Time": round(time, 6),
            "Machine": process,
            **fields,
        }
        self.events.append(ev)
        if severity >= SevError:
            self.error_count += 1
        if self._file:
            line = json.dumps(ev, default=str) + "\n"
            self._file.write(line)
            self._file.flush()
            self._file_bytes += len(line)
            if self.max_file_bytes and self._file_bytes >= self.max_file_bytes:
                self._roll()

    def _roll(self) -> None:
        """Rotate path → path.1 → … → path.N (oldest deleted), then reopen
        a fresh file. The live handle closes promptly so rolled files
        never pin descriptors."""
        self._file.close()
        self._file = None
        oldest = f"{self.path}.{self.keep_files}"
        if os.path.exists(oldest):
            os.remove(oldest)
        for i in range(self.keep_files - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        os.replace(self.path, f"{self.path}.1")
        self._file = open(self.path, "a")
        self._file_bytes = 0
        self.rolls += 1

    def rolled_paths(self) -> list[str]:
        """Existing rolled siblings, oldest first (for trace consumers)."""
        if not self.path:
            return []
        out = []
        for i in range(self.keep_files, 0, -1):
            p = f"{self.path}.{i}"
            if os.path.exists(p):
                out.append(p)
        return out

    def of_type(self, event_type: str) -> list[dict]:
        return [e for e in self.events if e["Type"] == event_type]

    def close(self):
        if self._file:
            self._file.close()
            self._file = None


_global_log = TraceLog(min_severity=SevInfo)


def set_trace_log(log: TraceLog) -> None:
    global _global_log
    _global_log = log


def trace_log() -> TraceLog:
    return _global_log


def trace(severity: int, event_type: str, process: str = "", **fields) -> None:
    from .loop import _current

    t = _current.now() if _current is not None else 0.0
    _global_log.log(severity, event_type, t, process, **fields)


# -- distributed spans ---------------------------------------------------------

SPAN_EVENT = "Span"

# the ambient active context: the SpanContext of the span (local or remote
# parent) the currently-running actor is inside. Mutated ONLY through
# swap_active_span — the futures machinery and the RPC dispatch paths own
# the save/restore discipline.
_active_span: Optional["SpanContext"] = None


class SpanContext:
    """(trace_id, span_id) of a sampled span — what crosses RPC hops.
    Only sampled contexts exist as objects; an unsampled trace is simply
    the absence of one (the reference's Span::context with sampled bit)."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str = ""):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self):
        return f"SpanContext({self.trace_id}/{self.span_id})"


class Span:
    """One timed stage of a trace. Emits a ``Type="Span"`` event at
    finish; ``with span(...)`` activates it as the ambient context so
    child spans and outbound RPCs inherit it."""

    __slots__ = ("name", "context", "parent_id", "process", "begin", "tags", "_prev", "_done")

    def __init__(self, name: str, context: SpanContext, parent_id: str, process: str, tags: dict):
        from .loop import _current

        self.name = name
        self.context = context
        self.parent_id = parent_id
        self.process = process
        self.begin = _current.now() if _current is not None else 0.0
        self.tags = tags
        self._prev = None
        self._done = False

    @property
    def sampled(self) -> bool:
        return True

    def tag(self, **kw) -> "Span":
        self.tags.update(kw)
        return self

    def event(self, event: str, kind: str = "CommitDebug", **fields) -> None:
        """Point annotation on this span's trace — emitted in the debug
        stream (tools/commit_chain.py's input), so the debug chains are
        now a span-layer product. Commit stages keep the ``CommitDebug``
        type (chain() output stays byte-stable for existing consumers);
        read-path stages use ``ReadDebug`` and join only opt-in chains."""
        trace(
            SevInfo, kind, self.process,
            Id=self.context.trace_id, Event=event, **fields,
        )

    def finish(self, end: Optional[float] = None) -> None:
        if self._done:
            return
        self._done = True
        from .loop import _current

        t = end if end is not None else (_current.now() if _current is not None else 0.0)
        trace(
            SevInfo, SPAN_EVENT, self.process,
            Trace=self.context.trace_id,
            SpanId=self.context.span_id,
            Parent=self.parent_id,
            Name=self.name,
            Begin=round(self.begin, 6),
            Dur=round(max(0.0, t - self.begin), 6),
            **self.tags,
        )

    # -- context-manager activation
    def __enter__(self) -> "Span":
        self._prev = swap_active_span(self.context)
        return self

    def __exit__(self, *exc) -> bool:
        swap_active_span(self._prev)
        self.finish()
        return False


class _NullSpan:
    """Shared no-op span for unsampled traces — every method is inert so
    instrumentation sites need no sampled-or-not branches."""

    __slots__ = ()
    sampled = False
    context = None

    def tag(self, **kw):
        return self

    def event(self, event: str, **fields) -> None:
        pass

    def finish(self, end=None) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> bool:
        return False


NULL_SPAN = _NullSpan()


def active_span() -> Optional[SpanContext]:
    return _active_span


def swap_active_span(ctx: Optional[SpanContext]) -> Optional[SpanContext]:
    global _active_span
    prev = _active_span
    _active_span = ctx
    return prev


def _next_span_id(process: str) -> str:
    """Span ids count up PER EVENT LOOP (same-seed sim runs replay the
    same ids) and carry the process name (distinct OS processes in a TCP
    cluster cannot collide inside one trace)."""
    from .loop import _current

    if _current is not None:
        n = getattr(_current, "_span_seq", 0) + 1
        _current._span_seq = n
    else:  # no loop (import-time/tooling): never travels, uniqueness moot
        n = 0
    return f"{process}:{n}" if process else f":{n}"


def span(name: str, process: str = "", parent=None, **tags):
    """Open a span under ``parent`` (a SpanContext/Span) or, by default,
    the ambient active context. No sampled ancestor → the no-op span."""
    ctx = parent.context if isinstance(parent, Span) else parent
    if ctx is None:
        ctx = _active_span
    if ctx is None:
        return NULL_SPAN
    return Span(name, SpanContext(ctx.trace_id, _next_span_id(process)), ctx.span_id, process, tags)


def emit_span(name: str, process: str, parent, begin: float, end: float, **tags) -> Optional[str]:
    """Record an already-elapsed stage as a finished span (batch pipelines
    measure first, attribute after). Returns the span id, or None when
    ``parent`` is unsampled."""
    ctx = parent.context if isinstance(parent, Span) else parent
    if ctx is None:
        return None
    sp = Span(name, SpanContext(ctx.trace_id, _next_span_id(process)), ctx.span_id, process, tags)
    sp.begin = begin
    sp.finish(end)
    return sp.context.span_id


def annotate(event: str, process: str = "", kind: str = "ReadDebug", **fields) -> None:
    """Point annotation on the ambient trace (no-op when unsampled) —
    emitted into the debug stream so tools/commit_chain.py full chains
    carry it."""
    if _active_span is not None:
        trace(SevInfo, kind, process, Id=_active_span.trace_id, Event=event, **fields)


def root_context(trace_id: str) -> SpanContext:
    """The root of a new sampled trace: spans parented to it carry
    Parent="" (waterfall roots). The trace_id doubles as the transaction
    debug id, so CommitDebug chains and spans share one identity."""
    return SpanContext(trace_id, "")
