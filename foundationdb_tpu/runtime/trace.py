"""Structured trace events — the analog of flow/Trace.cpp TraceEvent.

Events are dicts with severity, type, time, and process identity, collected
per run (and optionally mirrored to a JSONL file, the counterpart of the
reference's rolling XML trace logs — like the reference, the file rolls at
a size threshold, keeping a bounded set of numbered predecessors). A
SevError event marks the run failed — exactly the simulator's pass/fail
criterion (SURVEY.md §3.4).
"""

from __future__ import annotations

import json
import os
from typing import Optional

SevDebug, SevInfo, SevWarn, SevWarnAlways, SevError = 5, 10, 20, 30, 40

_SEV_NAMES = {5: "Debug", 10: "Info", 20: "Warn", 30: "WarnAlways", 40: "Error"}

# the reference rolls trace files at 10 MB (TraceLog's maxLogsSize /
# rollsize, flow/Trace.cpp) and prunes old ones; same defaults here —
# overridable per-log (tools pass Knobs.TRACE_ROLL_BYTES / _KEEP)
DEFAULT_ROLL_BYTES = 10 << 20
DEFAULT_ROLL_KEEP = 10


class TraceLog:
    def __init__(
        self,
        path: Optional[str] = None,
        min_severity: int = SevInfo,
        max_file_bytes: int = DEFAULT_ROLL_BYTES,
        keep_files: int = DEFAULT_ROLL_KEEP,
    ):
        self.events: list[dict] = []
        self.error_count = 0
        self.min_severity = min_severity
        self.path = path
        self.max_file_bytes = max_file_bytes
        self.keep_files = max(1, keep_files)
        self.rolls = 0
        self._file = open(path, "a") if path else None
        self._file_bytes = os.path.getsize(path) if path else 0

    def log(self, severity: int, event_type: str, time: float, process: str, **fields):
        if severity < self.min_severity:
            return
        ev = {
            "Severity": _SEV_NAMES.get(severity, severity),
            "Type": event_type,
            "Time": round(time, 6),
            "Machine": process,
            **fields,
        }
        self.events.append(ev)
        if severity >= SevError:
            self.error_count += 1
        if self._file:
            line = json.dumps(ev, default=str) + "\n"
            self._file.write(line)
            self._file.flush()
            self._file_bytes += len(line)
            if self.max_file_bytes and self._file_bytes >= self.max_file_bytes:
                self._roll()

    def _roll(self) -> None:
        """Rotate path → path.1 → … → path.N (oldest deleted), then reopen
        a fresh file. The live handle closes promptly so rolled files
        never pin descriptors."""
        self._file.close()
        self._file = None
        oldest = f"{self.path}.{self.keep_files}"
        if os.path.exists(oldest):
            os.remove(oldest)
        for i in range(self.keep_files - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        os.replace(self.path, f"{self.path}.1")
        self._file = open(self.path, "a")
        self._file_bytes = 0
        self.rolls += 1

    def rolled_paths(self) -> list[str]:
        """Existing rolled siblings, oldest first (for trace consumers)."""
        if not self.path:
            return []
        out = []
        for i in range(self.keep_files, 0, -1):
            p = f"{self.path}.{i}"
            if os.path.exists(p):
                out.append(p)
        return out

    def of_type(self, event_type: str) -> list[dict]:
        return [e for e in self.events if e["Type"] == event_type]

    def close(self):
        if self._file:
            self._file.close()
            self._file = None


_global_log = TraceLog(min_severity=SevInfo)


def set_trace_log(log: TraceLog) -> None:
    global _global_log
    _global_log = log


def trace_log() -> TraceLog:
    return _global_log


def trace(severity: int, event_type: str, process: str = "", **fields) -> None:
    from .loop import _current

    t = _current.now() if _current is not None else 0.0
    _global_log.log(severity, event_type, t, process, **fields)
