"""Structured trace events — the analog of flow/Trace.cpp TraceEvent.

Events are dicts with severity, type, time, and process identity, collected
per run (and optionally mirrored to a JSONL file, the counterpart of the
reference's rolling XML trace logs). A SevError event marks the run failed —
exactly the simulator's pass/fail criterion (SURVEY.md §3.4).
"""

from __future__ import annotations

import json
from typing import Optional

SevDebug, SevInfo, SevWarn, SevWarnAlways, SevError = 5, 10, 20, 30, 40

_SEV_NAMES = {5: "Debug", 10: "Info", 20: "Warn", 30: "WarnAlways", 40: "Error"}


class TraceLog:
    def __init__(self, path: Optional[str] = None, min_severity: int = SevInfo):
        self.events: list[dict] = []
        self.error_count = 0
        self.min_severity = min_severity
        self._file = open(path, "a") if path else None

    def log(self, severity: int, event_type: str, time: float, process: str, **fields):
        if severity < self.min_severity:
            return
        ev = {
            "Severity": _SEV_NAMES.get(severity, severity),
            "Type": event_type,
            "Time": round(time, 6),
            "Machine": process,
            **fields,
        }
        self.events.append(ev)
        if severity >= SevError:
            self.error_count += 1
        if self._file:
            self._file.write(json.dumps(ev, default=str) + "\n")
            self._file.flush()

    def of_type(self, event_type: str) -> list[dict]:
        return [e for e in self.events if e["Type"] == event_type]

    def close(self):
        if self._file:
            self._file.close()
            self._file = None


_global_log = TraceLog(min_severity=SevInfo)


def set_trace_log(log: TraceLog) -> None:
    global _global_log
    _global_log = log


def trace_log() -> TraceLog:
    return _global_log


def trace(severity: int, event_type: str, process: str = "", **fields) -> None:
    from .loop import _current

    t = _current.now() if _current is not None else 0.0
    _global_log.log(severity, event_type, t, process, **fields)
