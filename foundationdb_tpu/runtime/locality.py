"""Process locality + replication policy combinators.

The analog of the reference's ``fdbrpc/Locality.h`` (process locality keys:
machine / zone / datacenter) and ``fdbrpc/ReplicationPolicy.h:99-160``
(``PolicyOne`` / ``PolicyAcross`` / ``PolicyAnd``): declarative placement
constraints used for storage team building and tlog replica sets, so a
"2-replica" cluster puts its replicas in two different failure domains
instead of two processes on one machine.

A policy answers two questions:

- ``validate(localities)`` — does this concrete replica set satisfy the
  constraint?
- ``select(candidates)`` — choose a minimal satisfying set from
  ``(item, Locality)`` pairs, or None if impossible.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Sequence


@dataclass(frozen=True)
class Locality:
    """Where a process lives (fdbrpc/Locality.h). ``zone`` is the failure
    domain replication policies speak about by default; ``machine``
    defaults to the zone and ``dc`` groups zones into regions."""

    machine: str = ""
    zone: str = ""
    dc: str = ""

    def get(self, field: str) -> str:
        return getattr(self, field)

    @classmethod
    def of(cls, machine: str, zone: str = None, dc: str = "dc0") -> "Locality":
        return cls(machine=machine, zone=zone or machine, dc=dc)


class ReplicationPolicy:
    """Base combinator (fdbrpc/ReplicationPolicy.h:99)."""

    def replicas(self) -> int:
        raise NotImplementedError

    def validate(self, localities: Sequence[Locality]) -> bool:
        raise NotImplementedError

    def select(
        self, candidates: Sequence[tuple[Any, Locality]]
    ) -> Optional[list[Any]]:
        raise NotImplementedError


class PolicyOne(ReplicationPolicy):
    """Any single replica (ReplicationPolicy.h:110 PolicyOne)."""

    def replicas(self) -> int:
        return 1

    def validate(self, localities) -> bool:
        return len(localities) >= 1

    def select(self, candidates):
        return [candidates[0][0]] if candidates else None

    def __repr__(self):
        return "One()"


class PolicyAcross(ReplicationPolicy):
    """``n`` groups with distinct values of ``field``, each group
    satisfying ``inner`` (ReplicationPolicy.h:119 PolicyAcross) — e.g.
    Across(2, "zone", One()) = two replicas in two different zones."""

    def __init__(self, n: int, field: str = "zone", inner: ReplicationPolicy = None):
        self.n = n
        self.field = field
        self.inner = inner or PolicyOne()

    def replicas(self) -> int:
        return self.n * self.inner.replicas()

    def _groups(self, pairs):
        groups: dict[str, list] = {}
        for item, loc in pairs:
            groups.setdefault(loc.get(self.field), []).append((item, loc))
        return groups

    def validate(self, localities) -> bool:
        groups: dict[str, list] = {}
        for loc in localities:
            groups.setdefault(loc.get(self.field), []).append(loc)
        good = sum(1 for g in groups.values() if self.inner.validate(g))
        return good >= self.n

    def select(self, candidates):
        groups = self._groups(candidates)
        # favor the emptiest constraint first: groups with the most
        # candidates give the inner policy the best chance
        picked: list[Any] = []
        done = 0
        for _val, group in sorted(
            groups.items(), key=lambda kv: -len(kv[1])
        ):
            if done == self.n:
                break
            inner_pick = self.inner.select(group)
            if inner_pick is not None:
                picked.extend(inner_pick)
                done += 1
        return picked if done == self.n else None

    def __repr__(self):
        return f"Across({self.n},{self.field},{self.inner!r})"


class PolicyAnd(ReplicationPolicy):
    """All sub-policies must hold on the same set
    (ReplicationPolicy.h:146 PolicyAnd)."""

    def __init__(self, policies: Sequence[ReplicationPolicy]):
        self.policies = list(policies)

    def replicas(self) -> int:
        return max(p.replicas() for p in self.policies)

    def validate(self, localities) -> bool:
        return all(p.validate(localities) for p in self.policies)

    def select(self, candidates):
        # greedy: select for the strictest policy (most replicas), then
        # verify the rest; on failure, widen by adding candidates from
        # uncovered groups until all validate or we run out
        ordered = sorted(self.policies, key=lambda p: -p.replicas())
        picked = ordered[0].select(candidates)
        if picked is None:
            return None
        loc_of = {id(i): l for i, l in candidates}
        sel = list(picked)
        rest = [c for c in candidates if c[0] not in sel]
        while not self.validate([loc_of[id(i)] for i in sel]):
            if not rest:
                return None
            sel.append(rest.pop(0)[0])
        return sel

    def __repr__(self):
        return f"And({self.policies!r})"


def policy_for(replication: int, field: str = "zone") -> ReplicationPolicy:
    """The default policy for an N-replica configuration: N distinct
    failure domains (DatabaseConfiguration's single/double/triple)."""
    if replication <= 1:
        return PolicyOne()
    return PolicyAcross(replication, field)
