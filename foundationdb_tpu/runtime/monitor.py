"""SystemMonitor: periodic process-health trace events.

The analog of flow/SystemMonitor.cpp (systemMonitor → ProcessMetrics /
MachineMetrics / NetworkMetrics): every interval, one trace event with the
process's vitals — run-loop lag (scheduling delay of a zero-delay timer,
the reference's S2Pri/loop-busyness signal), live actor count, posted-
queue depth, memory use, and event-loop personality. Used by real servers
(tools/fdbserver spawns it per process) and available to sims."""

from __future__ import annotations

from .loop import current_loop, now
from .trace import SevInfo, trace


async def system_monitor(process, interval: float = 5.0):
    from .futures import delay

    loop = current_loop()
    last = now()
    while True:
        before = now()
        await delay(interval)
        lag = max(0.0, (now() - before) - interval)
        try:
            import resource

            rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        except Exception:
            rss_kb = 0
        coll = getattr(process, "actors", None)
        n_actors = len(getattr(coll, "_actors", []) or [])
        sample = dict(
            Elapsed=round(now() - last, 3),
            RunLoopLag=round(lag, 6),
            Actors=n_actors,
            Endpoints=len(getattr(process, "endpoints", {}) or {}),
            QueueDepth=len(getattr(loop, "_queue", []) or []),
            MemoryKB=rss_kb,
        )
        # latest sample stays readable on demand (the status document's
        # machine/process sections pull it through worker.systemMetrics)
        process.last_process_metrics = sample
        trace(
            SevInfo,
            "ProcessMetrics",
            getattr(process, "address", ""),
            **sample,
        )
        last = now()
