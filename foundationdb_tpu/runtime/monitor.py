"""SystemMonitor: periodic process-health trace events.

The analog of flow/SystemMonitor.cpp (systemMonitor → ProcessMetrics /
MachineMetrics / NetworkMetrics): every interval, one trace event with the
process's vitals — run-loop lag (scheduling delay of a zero-delay timer,
the reference's S2Pri/loop-busyness signal), live actor count, posted-
queue depth, memory use, and event-loop personality. Used by real servers
(tools/fdbserver spawns it per process) and available to sims."""

from __future__ import annotations

from .loop import current_loop, now
from .trace import SevInfo, trace


def memory_kb() -> tuple[int, int]:
    """(current RSS KB, peak RSS KB). ``ru_maxrss`` is the lifetime
    HIGH-WATER mark, not the current footprint — reporting it as MemoryKB
    made a post-spike process look permanently bloated. Current RSS comes
    from /proc/self/statm when available (Linux); elsewhere both report
    the rusage peak."""
    peak = 0
    try:
        import resource

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    except Exception:
        pass
    cur = peak
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])  # resident field
        import os

        cur = pages * os.sysconf("SC_PAGE_SIZE") // 1024
    except Exception:
        pass
    return cur, peak


async def system_monitor(process, interval: float = 5.0):
    from .futures import delay

    loop = current_loop()
    last = now()
    while True:
        before = now()
        await delay(interval)
        lag = max(0.0, (now() - before) - interval)
        rss_kb, peak_kb = memory_kb()
        coll = getattr(process, "actors", None)
        n_actors = len(getattr(coll, "_actors", []) or [])
        sample = dict(
            Elapsed=round(now() - last, 3),
            RunLoopLag=round(lag, 6),
            Actors=n_actors,
            Endpoints=len(getattr(process, "endpoints", {}) or {}),
            QueueDepth=len(getattr(loop, "_queue", []) or []),
            MemoryKB=rss_kb,
            PeakMemoryKB=peak_kb,
        )
        # run-loop profiler vitals (runtime/profiler.py) when installed:
        # the headline numbers an operator scans ProcessMetrics for before
        # reaching for `cli top` / the process.metrics snapshot
        prof = getattr(loop, "profiler", None)
        if prof is not None:
            sample["LoopSteps"] = prof._c_steps.value
            sample["SlowTasks"] = prof._c_slow.value
            sample["LoopBusyFraction"] = round(prof.busy_fraction(), 6)
        # latest sample stays readable on demand (the status document's
        # machine/process sections pull it through worker.systemMetrics)
        process.last_process_metrics = sample
        trace(
            SevInfo,
            "ProcessMetrics",
            getattr(process, "address", ""),
            **sample,
        )
        last = now()
