"""Binary serialization: length-prefixed, versioned archives.

The analog of flow/serialize.h (BinaryWriter/BinaryReader with
protocol-version stamps) — hand-rolled little-endian framing used by the
durable formats (DiskQueue entries, storage-engine snapshots, tlog
payloads). The simulator passes Python objects by reference, so this is
only on the durability path (and later the wire path of the C API).
"""

from __future__ import annotations

import struct

# fdb-tpu wire format generation. The codec decodes structs positionally
# (schema-by-convention), so ANY dataclass field change in a wire type
# MUST bump this — mixed-build processes then reject each other at the
# handshake instead of raising mid-stream.
# gen 2: GetCommitVersionRequest.applied_changes_version +
#        GetCommitVersionReply.resolver_changes[,_version]
# gen 3: TransactionData.debug_id (transaction debug chains)
# gen 4: request tuples carry a span-context envelope field
#        (distributed tracing; net/tcp.py "req" messages)
# gen 5: batched read pipeline — storage.multiGet / storage.multiGetRange
#        endpoints and their MultiGet*Request/Reply shapes (ISSUE 12)
# gen 6: GRV priority/tenant envelope
# gen 7: super-frame batched framing (net/wire.py BATCH_BIT frames;
#        receivers accept gen-6-shaped per-message frames too, but a
#        gen-6 build must not peer with a gen-7 one — the handshake
#        rejects the mix)
# gen 8: watches + change feeds — storage.feedRead streaming envelope
#        (FeedReadRequest/Reply whole-version pages riding the super-
#        frame path) and the known_committed frontier piggybacked on
#        TLogPeekReply; a gen-7 peer would decode peek replies
#        positionally wrong, so the handshake must reject it
PROTOCOL_VERSION = 0x0FDB00B070010009  # gen-9: proxy conflict pre-filter —
#        ResolveBatchReply grows committed_ranges + version_floor
#        (resolver→proxy summary feedback); the codec is positional, so a
#        gen-8 peer would misparse the reply tail — handshake rejects it
#
# NOT a generation bump: the schema-compiled codec (net/wire.py,
# WIRE_COMPILED_CODEC) emits byte-identical gen-9 frames — it changes how
# structs are packed/unpacked, never what lands on the wire. The
# tests/golden_wire.json fixture plus the fuzzed compiled-vs-interpretive
# differential in tests/test_wire_codec.py enforce that equivalence; a
# real field change still bumps the generation as before.


class BinaryWriter:
    __slots__ = ("_parts",)

    def __init__(self):
        self._parts: list[bytes] = []

    def u8(self, v: int) -> "BinaryWriter":
        self._parts.append(struct.pack("<B", v))
        return self

    def u32(self, v: int) -> "BinaryWriter":
        self._parts.append(struct.pack("<I", v))
        return self

    def i64(self, v: int) -> "BinaryWriter":
        self._parts.append(struct.pack("<q", v))
        return self

    def u64(self, v: int) -> "BinaryWriter":
        self._parts.append(struct.pack("<Q", v))
        return self

    def bytes_(self, b: bytes) -> "BinaryWriter":
        """Length-prefixed byte string."""
        self._parts.append(struct.pack("<I", len(b)))
        self._parts.append(b)
        return self

    def raw(self, b: bytes) -> "BinaryWriter":
        self._parts.append(b)
        return self

    def data(self) -> bytes:
        return b"".join(self._parts)


class BinaryReader:
    __slots__ = ("_buf", "_pos")

    def __init__(self, buf: bytes):
        self._buf = buf
        self._pos = 0

    def u8(self) -> int:
        (v,) = struct.unpack_from("<B", self._buf, self._pos)
        self._pos += 1
        return v

    def u32(self) -> int:
        (v,) = struct.unpack_from("<I", self._buf, self._pos)
        self._pos += 4
        return v

    def i64(self) -> int:
        (v,) = struct.unpack_from("<q", self._buf, self._pos)
        self._pos += 8
        return v

    def u64(self) -> int:
        (v,) = struct.unpack_from("<Q", self._buf, self._pos)
        self._pos += 8
        return v

    def bytes_(self) -> bytes:
        n = self.u32()
        v = self._buf[self._pos : self._pos + n]
        assert len(v) == n, "truncated archive"
        self._pos += n
        return v

    def remaining(self) -> int:
        return len(self._buf) - self._pos


# -- mutation codec (CommitTransaction.h wire shape) ---------------------------


def write_mutation(w: BinaryWriter, m) -> None:
    w.u8(int(m.type)).bytes_(m.param1).bytes_(m.param2 or b"")


def read_mutation(r: BinaryReader):
    from ..kv.mutations import Mutation, MutationType

    t = MutationType(r.u8())
    p1 = r.bytes_()
    p2 = r.bytes_()
    return Mutation(t, p1, p2)


def write_tagged_messages(version: int, messages: dict) -> bytes:
    """One tlog entry: version + {tag: [mutations]}."""
    w = BinaryWriter()
    w.i64(version)
    w.u32(len(messages))
    for tag, muts in messages.items():
        w.i64(tag)
        w.u32(len(muts))
        for m in muts:
            write_mutation(w, m)
    return w.data()


def read_tagged_messages(buf: bytes):
    r = BinaryReader(buf)
    version = r.i64()
    n_tags = r.u32()
    messages = {}
    for _ in range(n_tags):
        tag = r.i64()
        n = r.u32()
        messages[tag] = [read_mutation(r) for _ in range(n)]
    return version, messages
