"""BUGGIFY — seeded random activation of rare code paths in simulation.

The analog of flow/flow.h:54-67 + flow/flow.cpp:178-199: each call site is
identified by (file, line); per run, a site is first decided "enabled" with
probability p_enabled, and an enabled site then fires with p_fire per
evaluation. Outside simulation every site is off.
"""

from __future__ import annotations

import inspect
from typing import Optional

from .rng import DeterministicRandom


class Buggify:
    def __init__(self, rng: Optional[DeterministicRandom], p_enabled=0.25, p_fire=0.25):
        self.rng = rng
        self.p_enabled = p_enabled
        self.p_fire = p_fire
        self._sites: dict[tuple[str, int], bool] = {}
        self.fired: set[tuple[str, int]] = set()

    def __call__(self, site: Optional[tuple] = None, _depth: int = 1) -> bool:
        if self.rng is None:
            return False
        if site is None:
            fr = inspect.currentframe()
            for _ in range(_depth):
                fr = fr.f_back
            site = (fr.f_code.co_filename, fr.f_lineno)
        if site not in self._sites:
            self._sites[site] = self.rng.coinflip(self.p_enabled)
        if self._sites[site] and self.rng.coinflip(self.p_fire):
            self.fired.add(site)
            return True
        return False


_buggify = Buggify(None)


def set_buggify(b: Buggify) -> None:
    global _buggify
    _buggify = b


def buggify(site: Optional[tuple] = None) -> bool:
    # _depth=2: attribute the site to the caller of this wrapper, not the
    # wrapper itself — otherwise every call site collapses to one key.
    return _buggify(site, _depth=2)


def mark_fired(site: tuple) -> None:
    """Record an externally-decided chaos event (e.g. the kernel fault
    injector's own seeded-RNG rolls, conflict/faults.py) in this run's
    buggify coverage, so the soak's fired-site report sees every fault
    source — not only the buggify()-gated ones. No-op outside simulation."""
    if _buggify.rng is not None:
        _buggify.fired.add(site)
