"""Seeded deterministic PRNG — the analog of flow/DeterministicRandom.h.

Every source of nondeterminism in simulation (task latencies, clogging,
buggify activation, workload data) draws from one of these, so a failing run
replays exactly from its seed.
"""

from __future__ import annotations

import random as _random


class DeterministicRandom:
    def __init__(self, seed: int):
        self.seed = seed
        self._r = _random.Random(seed)

    def random01(self) -> float:
        return self._r.random()

    def random_int(self, lo: int, hi: int) -> int:
        """Uniform in [lo, hi) — matches the reference's randomInt convention."""
        return self._r.randrange(lo, hi)

    def random_choice(self, seq):
        return seq[self._r.randrange(0, len(seq))]

    def random_unique_id(self) -> str:
        return f"{self._r.getrandbits(64):016x}"

    def coinflip(self, p: float = 0.5) -> bool:
        return self._r.random() < p

    def shuffle(self, lst) -> None:
        self._r.shuffle(lst)

    def fork(self) -> "DeterministicRandom":
        """Derive an independent deterministic stream."""
        return DeterministicRandom(self._r.getrandbits(63))
