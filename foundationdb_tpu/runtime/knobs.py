"""Config knobs — the analog of flow/Knobs.cpp (Flow/Client/Server knobs).

Defaults live here; simulation randomizes a subset per run (the reference's
BUGGIFY-aware knob randomization, SURVEY.md §5.6); anything can be overridden
by name (the --knob_name flag path, fdbserver.actor.cpp:923).
"""

from __future__ import annotations


class Knobs:
    # commit pipeline (reference: COMMIT_TRANSACTION_BATCH_INTERVAL_MIN
    # 1 ms / _FROM_IDLE 0.5 ms, fdbserver/Knobs.cpp:221-223)
    COMMIT_BATCH_INTERVAL = 0.001  # proxy batch window (s)
    COMMIT_BATCH_INTERVAL_FROM_IDLE = 0.0005  # first batch after idle
    MAX_COMMIT_BATCH_INTERVAL = 0.25  # idle proxies commit empty batches
    MAX_BATCH_TXNS = 4096
    # bound on phase-1's wait for the master's version grant: past this the
    # request is presumed dropped (partition) and the batch errors as
    # commit_unknown_result instead of wedging the gate chain. Sized past
    # the master's 4s gap-abandonment window so a merely-slow grant that
    # the master still honors isn't double-assigned.
    GETCOMMITVERSION_TIMEOUT = 6.0
    # how long the master parks an out-of-order version request for its
    # missing predecessor before abandoning the gap (a partition ate it)
    MASTER_VERSION_GAP_TIMEOUT = 4.0
    # consecutive master-unreachable batch failures before a proxy
    # declares its master dead and retires
    PROXY_MASTER_MISS_LIMIT = 8
    VERSIONS_PER_SECOND = 1_000_000
    MAX_READ_TRANSACTION_LIFE_VERSIONS = 5_000_000  # the MVCC window (~5s)
    MAX_VERSIONS_IN_FLIGHT = 100_000_000
    # conflict set
    CONFLICT_SET_BACKEND = "tpu"  # tpu | native | oracle (newConflictSet knob)
    CONFLICT_SET_CAPACITY = 1 << 14
    # conflict-kernel fault tolerance (conflict/failover.py + resolver):
    # per-batch deadline on the device dispatch/collect path, bounded
    # in-place retry for transient faults, then journal-replay recovery
    # escalating to failover onto the native/oracle backend
    CONFLICT_DISPATCH_DEADLINE = 2.0  # s per batch before the device is presumed wedged
    CONFLICT_DISPATCH_RETRIES = 3  # in-place dispatch retries (transient errors)
    CONFLICT_RETRY_BACKOFF = 0.02  # base retry backoff (s, doubles per attempt)
    CONFLICT_FAILOVER_STRIKES = 3  # recovery resolves before failing over
    CONFLICT_REBUILD_ATTEMPTS = 2  # device rebuild tries per recovery resolve
    CONFLICT_REPROBE_INTERVAL = 1.0  # probe cadence for device re-promotion (s)
    CONFLICT_JOURNAL_CAPACITY = 512  # journaled committed-write batches kept
    # double-buffered dispatch: size of the resolver's dedicated host
    # encode executor (batch N encodes while batch N-1 scans on device);
    # 0 = encode synchronously inside the dispatch job (pre-overlap shape)
    CONFLICT_ENCODE_THREADS = 1
    # occupancy-driven proactive resharding (between batches, never
    # stalling a live dispatch): rebalance when collected staging/kept
    # pressure crosses this fraction of the slot ceiling…
    CONFLICT_RESHARD_PRESSURE = 0.75
    # …and grow the bucket count when live rows fill this fraction of grid
    CONFLICT_GROW_FILL = 0.5
    # sim-only seeded device-fault injection at the conflict seam
    # (conflict/faults.py): dispatch errors, hangs, device loss, stalls
    CONFLICT_FAULT_INJECTION = False
    # proxy conflict pre-filter (ISSUE 17, conflict/prefilter.py): a
    # decaying summary of recently committed write ranges, fed from
    # feedback piggybacked on resolver replies and consulted BEFORE a
    # transaction joins a commit batch — a doomed transaction fails with
    # the normal retryable not_committed without paying the version
    # grant, resolver codec, or tlog round trip. Strictly conservative
    # (rejects only on a stored committed range that provably overlaps a
    # read at a newer version); off = the pre-PR path (one-build A/B)
    PROXY_CONFLICT_PREFILTER = True
    # bucket key = first N bytes of a range's begin key (coarse interval
    # bloom granularity): smaller prefixes alias more writes per bucket
    # (cheaper, blunter), longer ones spread them out
    PREFILTER_PREFIX_LEN = 6
    # exact (begin, end, version) entries kept per bucket; overflow
    # evicts oldest-first, which only FORGETS conflicts (conservative)
    PREFILTER_BUCKET_ENTRIES = 32
    # buckets kept per proxy; overflow evicts the stalest bucket
    PREFILTER_MAX_BUCKETS = 4096
    # entries on the wide-range side list (ranges spanning > 1 bucket)
    PREFILTER_WIDE_RANGES = 128
    # committed-write ranges a resolver echoes per reply (newest win;
    # truncation only delays learning — conservative)
    PREFILTER_FEEDBACK_MAX_RANGES = 512
    # storage
    STORAGE_DURABILITY_LAG = 0.5  # how far behind durable version may trail (s)
    STORAGE_WAIT_VERSION_TIMEOUT = 1.0  # then future_version (client retries)
    STORAGE_FETCH_KEYS_BATCH = 10_000
    # epoch-batched storage engine (ISSUE 15 / ROADMAP item 5): the pull
    # loop applies each mutation batch as ONE epoch (sorted-index merge
    # once per batch, native range tombstones), reads pin O(1) immutable
    # snapshots, and the durability drain is clamped by active pins. Off
    # = the legacy per-mutation apply path (one-build A/B).
    STORAGE_EPOCH_BATCHING = True
    # scan lease: a chunked read that replied `more` pins its version for
    # this long (refreshed per chunk) so multi-chunk scans, fetchKeys and
    # backup pages stop racing durability advances into TOO_OLD restarts
    STORAGE_SNAPSHOT_LEASE = 2.0
    # bound on how far a pin may hold the durability horizon behind the
    # tip: past this the advance proceeds and the pin goes TOO_OLD (an
    # abandoned pin must not grow the MVCC window without limit)
    STORAGE_PIN_MAX_LAG_VERSIONS = 10_000_000
    # watches & change feeds (ISSUE 16 / ROADMAP item 6): parked watch
    # registrations per storage server — past this, registration fails
    # with the typed retryable TooManyWatches and the client backs off
    # (reference: MAX_STORAGE_SERVER_WATCH_BYTES; sized so 100K-watch
    # storms fit with an order of magnitude to spare)
    STORAGE_WATCH_LIMIT = 1_000_000
    # change-feed retention: per-epoch committed diffs are kept for this
    # many versions behind the tip (~= seconds × VERSIONS_PER_SECOND).
    # Resuming below the retained floor raises TOO_OLD. Active subscriber
    # leases hold the floor (like scan-lease pins), bounded at 2x this.
    STORAGE_FEED_RETENTION_VERSIONS = 5_000_000
    # entries per change-feed read reply before `more` paging kicks in
    STORAGE_FEED_BATCH_ENTRIES = 1_000
    # TPU batched-read snapshot index on the storage read path
    # (SURVEY.md's secondary target): serves batch_get misses and
    # getRange bounds, delta-merged each durability epoch. None = AUTO:
    # on under simulation loops, off on a RealLoop (a real server must
    # not lazily initialize JAX per durability epoch on a shared-tunnel
    # host that can hang); True/False force it either way.
    STORAGE_TPU_INDEX = None
    # tlog
    TLOG_SPILL_THRESHOLD = 1 << 20
    TLOG_FSYNC_TIME = 0.0002  # modeled DiskQueue sync (SSD-class fsync)
    # commit path at wire speed (ISSUE 18) — all three are A/B'd together
    # by BENCH_COMPONENT=commit_path and drawn both ways by the soak's
    # randomize_commit_path(). Wire bytes are identical either way.
    # schema-compiled struct encode/decode in net/wire.py (process-wide:
    # the codec registry is module state)
    WIRE_COMPILED_CODEC = True
    # batch-settle reply/fan-out futures in one loop step
    # (futures.settle_batch; process-wide module state)
    FUTURE_SLAB_SETTLE = True
    # tlog releases the version chain at DiskQueue push time, overlapping
    # the next version's push with the in-flight write+fsync round; acks
    # still wait for the covering round's fsync (server/tlog.py)
    TLOG_FSYNC_PIPELINE = True
    # multi-region log routing
    ROUTER_BUFFER_BYTES = 1 << 20  # per-tag unacked relay buffer cap
    # data distribution (DataDistributionTracker.actor.cpp knobs
    # SHARD_MAX_BYTES_PER... scaled to sim data volumes)
    DD_SHARD_MAX_BYTES = 1 << 18  # split above this
    DD_SHARD_MIN_BYTES = 1 << 15  # merge adjacent same-team shards below
    DD_TRACKER_INTERVAL = 2.0
    DD_MOVE_THROTTLE = 0.5  # min delay between relocations (move queue)
    # failure detection / recovery
    HEARTBEAT_INTERVAL = 0.5
    FAILURE_TIMEOUT = 2.0
    # resolutionBalancing (masterserver.actor.cpp:896): load-driven moves
    # of key-range boundaries between resolver roles
    RESOLUTION_BALANCING_INTERVAL = 1.0  # master poll period (s)
    RESOLUTION_BALANCE_MIN_OPS = 200  # min per-interval imbalance to act
    RESOLUTION_BALANCE_RATIO = 1.5  # max/min load ratio that triggers a move
    RESOLUTION_SAMPLE_KEYS = 4096  # per-resolver load sample cap
    # ratekeeper (multi-signal admission control, ISSUE 13): per-class
    # rates from storage lag + tlog queue depth + run-loop busy fraction
    # + latency-band overrun + conflict-kernel health
    RK_POLL_INTERVAL = 0.5  # proxy -> master getRate cadence
    RK_MAX_TPS = 100_000.0
    RK_LAG_TARGET = 2_000_000  # start throttling here (versions)
    RK_LAG_MAX = 4_000_000  # floor rate here (MVCC window is 5M)
    # master does NOT gate admission entirely off: the floor keeps the
    # cluster draining (progress is what shrinks every signal)
    RK_RATE_FLOOR = 0.05  # default-class floor as a fraction of RK_MAX_TPS
    # worst storage durable-version lag (version - durableVersion): the
    # write-queue signal (limitReason storage_server_write_queue_size).
    # Calibrated ABOVE the sim's healthy steady state (~4.5-5.5M versions:
    # versions advance at 1M/s and durability batches seconds behind) so
    # only growth beyond baseline throttles
    RK_DURABILITY_LAG_TARGET = 6_000_000
    RK_DURABILITY_LAG_MAX = 12_000_000
    # worst tlog DiskQueue backlog (bytes not yet popped by consumers)
    RK_TLOG_QUEUE_TARGET = 2 << 20
    RK_TLOG_QUEUE_MAX = 8 << 20
    # run-loop busy fraction (PR 9's profiler gauge; REAL personality
    # only — a sim loop is busy by construction)
    RK_BUSY_FRACTION_TARGET = 0.90
    RK_BUSY_FRACTION_MAX = 0.98
    # latency-band overrun: fraction of proxy GRV/commit requests in the
    # poll interval that landed above RK_BAND_SLO seconds
    RK_BAND_SLO = 0.5
    RK_BAND_OVERRUN_TARGET = 0.05
    RK_BAND_OVERRUN_MAX = 0.25
    # conflict-kernel health (kernel.health): a DEGRADED kernel tightens
    # admission instead of queueing resolve batches into the dispatch
    # deadline; FAILED_OVER runs on the (slower) native backend
    RK_KERNEL_DEGRADED_FACTOR = 0.5
    RK_KERNEL_FAILED_OVER_FACTOR = 0.75
    # batch class throttles FIRST: its thresholds sit at this fraction of
    # the default class's targets (shed-order batch -> default -> immediate)
    RK_BATCH_SENSITIVITY = 0.5
    RK_RATE_SMOOTHING = 0.5  # exponential smoothing of per-class rates
    # proxy admission queue (server/admission.py): bounded depth per
    # class; waiters past their deadline shed with grv_throttled
    RK_GRV_QUEUE_MAX = 512  # per class per proxy
    RK_GRV_QUEUE_TIMEOUT = 0.5  # default-class queue deadline (s);
    #                             batch waits 0.5x, immediate 2x
    RK_ADMISSION_TICK = 0.02  # pump cadence while waiters are parked (s)
    RK_TENANT_MAX_SHARE = 0.5  # one tenant's cap as a fraction of the
    #                            default-class per-proxy rate
    RK_STATUS_TENANTS = 8  # per-tenant top-N surfaced through status
    # observability
    # run-loop profiler (runtime/profiler.py): per-actor busy attribution,
    # per-priority starvation, SlowTask events (the reference's run-loop
    # profiler + NetworkMetrics, flow/Net2.actor.cpp)
    RUN_LOOP_PROFILER = True
    RUN_LOOP_SLOW_TASK_MS = 50.0  # real-loop callbacks above this trace SlowTask
    PROFILER_SAMPLE_HZ = 100.0  # flame sampler rate (cli profile)
    TRACE_ROLL_BYTES = 10 << 20  # roll the JSONL trace file here (reference: 10 MB)
    TRACE_ROLL_KEEP = 10  # rolled files kept (path.1 .. path.N)
    # fraction of client transactions that open a sampled distributed
    # trace (runtime/trace.py spans; drawn from the client's seeded RNG so
    # same-seed sim runs sample identical trace_ids)
    TRACE_SAMPLE_RATE = 0.0
    LATENCY_PROBE_INTERVAL = 1.0  # CC's timed GRV/read/commit probe cadence
    METRICS_TRACE_INTERVAL = 5.0  # per-role CounterCollection trace cadence
    # keyspace telemetry (ISSUE 20, server/storage_metrics.py): sampled
    # byte/bandwidth estimation + read-hot-range detection on every
    # storage server (StorageMetrics.actor.h byteSample/getReadHotRanges)
    STORAGE_METRICS_SAMPLING = True
    STORAGE_BYTE_SAMPLE_FACTOR = 200  # P(sample a set) = size/FACTOR, capped at 1
    STORAGE_READ_SAMPLE_FACTOR = 400  # same for read-byte sampling
    STORAGE_READ_SAMPLE_MAX_KEYS = 4096  # read sample cap (smallest-weight eviction)
    STORAGE_METRICS_WINDOW = 5.0  # bandwidth/ops rolling-window width (s)
    STORAGE_HOT_RANGE_BUCKET_SAMPLES = 8  # byte-sample keys per hot-range bucket
    STORAGE_HOT_RANGE_MIN_DENSITY = 2.0  # status only surfaces density >= this
    STORAGE_HOT_RANGE_STATUS_N = 3  # per-storage top-N in the status gauge
    DD_WAIT_METRICS_SIZING = True  # DD sizes shards from waitMetrics pushes
    DD_WAIT_METRICS_TIMEOUT = 30.0  # re-arm cadence when no push arrives (s)
    # bounded metrics history (runtime/timeseries.py): every hosted
    # CounterCollection keeps a ring of numeric snapshots, read back via
    # worker.metricsHistory / cli metrics / trace_analyze --timeline
    METRICS_HISTORY_ENABLED = True
    METRICS_HISTORY_INTERVAL = 2.0  # snapshot cadence (s)
    METRICS_HISTORY_SAMPLES = 120  # ring capacity (points kept per role)
    # client
    # fraction of commits auto-tagged with a transaction-debug id
    # (g_traceBatch sampling; tr.set_debug_id forces one)
    CLIENT_COMMIT_SAMPLE = 0.0
    GRV_BATCH_INTERVAL = 0.0005
    CLIENT_MAX_RETRY_DELAY = 1.0
    # read pipeline (ISSUE 12 / ROADMAP item 1): same-tick coalescing of
    # client reads into storage multiGet/multiGetRange batches
    # (client/read_coalescer.py). Off = every read is its own RPC (the
    # pre-pipeline shape; the differential battery runs both).
    CLIENT_READ_COALESCING = True
    CLIENT_MULTIGET_MAX_KEYS = 1024  # entries per batched request
    # batched reads a storage connection keeps in flight per team before
    # new reads queue into the next batch (read pipelining, not
    # stop-and-wait: batch N+1 dispatches while batch N's reply is on
    # the wire)
    CLIENT_READ_PIPELINE_DEPTH = 4
    # transport v2 (ISSUE 14 / ROADMAP item 6): frame-batched zero-copy
    # wire path. Batching selects what a sender EMITS (gen-7 super-frames
    # vs gen-6 per-message frames — receivers accept both, so the A/B
    # runs within one build); loopback short-circuits colocated worlds in
    # the same OS process onto an in-process byte path (net/loopback.py)
    TRANSPORT_FRAME_BATCHING = True
    TRANSPORT_LOOPBACK = True
    TRANSPORT_RECV_BYTES = 1 << 16  # preallocated recv buffer (grows on demand)
    TRANSPORT_COMPACT_WATERMARK = 1 << 16  # consumed bytes before compaction
    TRANSPORT_MAX_BATCH_MESSAGES = 512  # messages per super-frame before early flush
    # sim-only transport chaos: super-frame truncation / partial-flush
    # site — a faulted request's caller sees a typed retryable error
    # (TransportTruncated), never a wedged connection
    TRANSPORT_FAULT_INJECTION = False
    # simulation (Sim2's latency model: MIN + FAST·a almost always, rare
    # tail to MAX — flow/Knobs.cpp:106-108, sim2.actor.cpp:1618)
    SIM_MIN_LATENCY = 0.0001
    SIM_FAST_LATENCY = 0.0008
    SIM_MAX_LATENCY = 0.003
    SIM_CLOG_MAX = 2.0
    SIM_FILE_SYNC_TIME = 0.0005  # modeled fsync of a simulated file
    SIM_FILE_WRITE_TIME = 0.00005

    def __init__(self, **overrides):
        for k, v in overrides.items():
            if not hasattr(type(self), k):
                raise KeyError(f"unknown knob {k!r}")
            setattr(self, k, v)

    def as_dict(self) -> dict:
        """Every knob value (for the wire codec and --knob tooling)."""
        return {
            k: getattr(self, k)
            for k in dir(type(self))
            if k.isupper() and not k.startswith("_")
        }

    def randomize(self, rng) -> None:
        """Buggify-style knob randomization for simulation runs (the
        reference randomizes knob defaults per sim run — BUGGIFY-aware
        defaults in */Knobs.cpp). Every choice is a legal configuration;
        extreme values exist to force rare paths (tiny batches, tiny spill
        thresholds, aggressive timeouts)."""
        if rng.coinflip(0.25):
            self.COMMIT_BATCH_INTERVAL = rng.random_choice([0.0005, 0.002, 0.01])
        if rng.coinflip(0.25):
            self.GRV_BATCH_INTERVAL = rng.random_choice([0.0002, 0.001, 0.005])
        if rng.coinflip(0.25):
            self.MAX_BATCH_TXNS = rng.random_choice([8, 64, 1024])
        if rng.coinflip(0.25):
            self.CONFLICT_SET_CAPACITY = rng.random_choice([16, 256, 1 << 12])
        if rng.coinflip(0.25):
            self.MAX_COMMIT_BATCH_INTERVAL = rng.random_choice([0.02, 0.1, 0.25])
        if rng.coinflip(0.25):
            self.TLOG_SPILL_THRESHOLD = rng.random_choice([256, 4096, 1 << 20])
        if rng.coinflip(0.25):
            self.STORAGE_DURABILITY_LAG = rng.random_choice([0.05, 0.5, 1.5])
        if rng.coinflip(0.25):
            self.STORAGE_FETCH_KEYS_BATCH = rng.random_choice([2, 64, 10_000])
        if rng.coinflip(0.25):
            self.HEARTBEAT_INTERVAL = rng.random_choice([0.2, 0.5, 1.0])
        if rng.coinflip(0.25):
            self.FAILURE_TIMEOUT = rng.random_choice([1.0, 2.0, 4.0])
        if rng.coinflip(0.25):
            self.CLIENT_MAX_RETRY_DELAY = rng.random_choice([0.2, 1.0])
        # coupled constraint: the failure detector must tolerate several
        # heartbeat periods (including a buggify-doubled one), or workers
        # flap out of the registry and recruitment never settles
        self.FAILURE_TIMEOUT = max(
            self.FAILURE_TIMEOUT, self.HEARTBEAT_INTERVAL * 4
        )
        if rng.coinflip(0.25):
            self.SIM_MAX_LATENCY = rng.random_choice([0.001, 0.003, 0.02])
        if rng.coinflip(0.25):
            self.SIM_FAST_LATENCY = rng.random_choice([0.0002, 0.0008, 0.004])
        if rng.coinflip(0.25):
            self.COMMIT_BATCH_INTERVAL_FROM_IDLE = rng.random_choice(
                [0.0001, 0.0005, 0.005]
            )
        if rng.coinflip(0.25):
            self.ROUTER_BUFFER_BYTES = rng.random_choice([512, 1 << 14, 1 << 20])
        if rng.coinflip(0.25):
            self.DD_SHARD_MAX_BYTES = rng.random_choice([2048, 1 << 16, 1 << 18])
            self.DD_SHARD_MIN_BYTES = self.DD_SHARD_MAX_BYTES // 8
        if rng.coinflip(0.25):
            self.DD_TRACKER_INTERVAL = rng.random_choice([0.3, 2.0, 10.0])
        if rng.coinflip(0.25):
            self.DD_MOVE_THROTTLE = rng.random_choice([0.0, 0.5, 2.0])
        if rng.coinflip(0.25):
            self.RK_MAX_TPS = rng.random_choice([500.0, 10_000.0, 100_000.0])
        if rng.coinflip(0.25):
            self.GRV_BATCH_INTERVAL = rng.random_choice([0.0002, 0.0005, 0.002])
        if rng.coinflip(0.25):
            self.TLOG_FSYNC_TIME = rng.random_choice([0.00005, 0.0002, 0.002])
        if rng.coinflip(0.25):
            self.MASTER_VERSION_GAP_TIMEOUT = rng.random_choice([1.0, 4.0, 8.0])
        if rng.coinflip(0.25):
            self.PROXY_MASTER_MISS_LIMIT = rng.random_choice([3, 8, 20])
        if rng.coinflip(0.25):
            self.RK_POLL_INTERVAL = rng.random_choice([0.1, 0.5, 1.5])
        if rng.coinflip(0.25):
            self.STORAGE_WAIT_VERSION_TIMEOUT = rng.random_choice([0.3, 1.0, 3.0])
        if rng.coinflip(0.25):
            self.SIM_FILE_SYNC_TIME = rng.random_choice([0.0001, 0.0005, 0.005])
        if rng.coinflip(0.25):
            self.RESOLUTION_BALANCING_INTERVAL = rng.random_choice([0.3, 1.0, 5.0])
        if rng.coinflip(0.25):
            self.RESOLUTION_BALANCE_MIN_OPS = rng.random_choice([50, 200, 1000])
        if rng.coinflip(0.25):
            self.LATENCY_PROBE_INTERVAL = rng.random_choice([0.5, 1.0, 5.0])
        if rng.coinflip(0.25):
            self.METRICS_TRACE_INTERVAL = rng.random_choice([1.0, 5.0, 10.0])
        if rng.coinflip(0.25):
            self.CONFLICT_DISPATCH_DEADLINE = rng.random_choice([0.5, 2.0, 5.0])
        if rng.coinflip(0.25):
            self.CONFLICT_FAILOVER_STRIKES = rng.random_choice([2, 3, 5])
        if rng.coinflip(0.25):
            self.CONFLICT_REPROBE_INTERVAL = rng.random_choice([0.3, 1.0, 3.0])
        if rng.coinflip(0.25):
            self.CONFLICT_JOURNAL_CAPACITY = rng.random_choice([64, 512, 2048])
        if rng.coinflip(0.25):
            # 0 exercises the legacy encode-in-dispatch shape; >0 the
            # double-buffered path (inline in sim, but with the early
            # pre-gate encode ordering and its stale-encoding window)
            self.CONFLICT_ENCODE_THREADS = rng.random_choice([0, 1, 2])
        if rng.coinflip(0.25):
            self.CONFLICT_RESHARD_PRESSURE = rng.random_choice([0.5, 0.75, 0.9])
        if rng.coinflip(0.25):
            self.CONFLICT_GROW_FILL = rng.random_choice([0.25, 0.5, 0.8])
        # coupled constraint: a proxy must keep waiting for a version
        # grant at least as long as the master might legitimately park it
        # behind a gap, or slow-but-honored grants get double-assigned
        self.GETCOMMITVERSION_TIMEOUT = max(
            self.GETCOMMITVERSION_TIMEOUT,
            self.MASTER_VERSION_GAP_TIMEOUT + 2.0,
        )

    def randomize_admission(self, rng) -> None:
        """Admission-control knob randomization (ISSUE 13), kept OUT of
        randomize() for the same pinned-seed reason as the read-pipeline
        knobs: the soak draws these at the very END of its sequence so
        every pinned chaos seed's cluster shape and workload rotation
        reproduce exactly. Capacity (RK_MAX_TPS) already randomizes in
        randomize(); these shape the queue/shed/tenant behavior only."""
        if rng.coinflip(0.25):
            # tiny queues force the shed-on-arrival path
            self.RK_GRV_QUEUE_MAX = rng.random_choice([8, 64, 512])
        if rng.coinflip(0.25):
            self.RK_GRV_QUEUE_TIMEOUT = rng.random_choice([0.1, 0.5, 2.0])
        if rng.coinflip(0.25):
            self.RK_TENANT_MAX_SHARE = rng.random_choice([0.25, 0.5, 1.0])
        if rng.coinflip(0.25):
            self.RK_BATCH_SENSITIVITY = rng.random_choice([0.25, 0.5, 0.75])
        if rng.coinflip(0.25):
            self.RK_ADMISSION_TICK = rng.random_choice([0.005, 0.02, 0.05])

    def randomize_transport(self, rng) -> None:
        """Transport-knob randomization (ISSUE 14), drawn at the very END
        of the soak's sequence (after randomize_admission) for the same
        pinned-seed reason as the read-pipeline/admission draws: the
        earlier cluster-shape and workload-rotation draws must reproduce
        exactly. Arming TRANSPORT_FAULT_INJECTION makes the soak call
        ``sim.arm_transport_faults`` with a DEDICATED forked rng, so even
        the armed runs leave the main chaos stream untouched."""
        if rng.coinflip(0.25):
            # both framings stay exercised across the soak matrix
            self.TRANSPORT_FRAME_BATCHING = rng.random_choice([True, False])
        if rng.coinflip(0.25):
            # tiny caps force the early-flush path
            self.TRANSPORT_MAX_BATCH_MESSAGES = rng.random_choice([2, 64, 512])
        if rng.coinflip(0.25):
            # tiny watermarks force constant compaction
            self.TRANSPORT_COMPACT_WATERMARK = rng.random_choice(
                [1 << 12, 1 << 16]
            )
        if rng.coinflip(0.3):
            self.TRANSPORT_FAULT_INJECTION = True

    def randomize_storage_engine(self, rng) -> None:
        """Storage-engine knob randomization (ISSUE 15), drawn at the very
        END of the soak's sequence (after the transport draws) for the
        pinned-seed reason shared by every post-PR-12 satellite: earlier
        cluster-shape and workload-rotation draws must reproduce exactly.
        The knob is consulted when a StorageServer constructs — in the
        soak that happens inside the sim run (worker recruitment), after
        these draws land."""
        if rng.coinflip(0.25):
            # both engine personalities stay exercised across the matrix
            self.STORAGE_EPOCH_BATCHING = rng.random_choice([True, False])
        if rng.coinflip(0.25):
            # tiny leases force the TOO_OLD-restart path; long ones hold
            # the durability horizon across whole scans
            self.STORAGE_SNAPSHOT_LEASE = rng.random_choice([0.05, 2.0, 10.0])
        if rng.coinflip(0.25):
            # a tight pin cap forces the forced-advance pin invalidation
            self.STORAGE_PIN_MAX_LAG_VERSIONS = rng.random_choice(
                [6_000_000, 10_000_000, 50_000_000]
            )

    def randomize_watches(self, rng) -> None:
        """Watch/change-feed knob randomization (ISSUE 16), drawn at the
        very END of the soak's sequence (after randomize_storage_engine)
        for the pinned-seed reason shared by every post-PR-12 satellite:
        earlier cluster-shape and workload-rotation draws must reproduce
        exactly. Tiny limits force the TooManyWatches backoff path; tiny
        retention forces feed TOO_OLD resumes."""
        if rng.coinflip(0.25):
            # tiny limits force the over-limit error + client backoff
            self.STORAGE_WATCH_LIMIT = rng.random_choice([4, 64, 1_000_000])
        if rng.coinflip(0.25):
            # tiny retention forces feed resume-below-floor TOO_OLD
            self.STORAGE_FEED_RETENTION_VERSIONS = rng.random_choice(
                [200_000, 1_000_000, 5_000_000]
            )
        if rng.coinflip(0.25):
            # tiny pages force the `more` continuation path
            self.STORAGE_FEED_BATCH_ENTRIES = rng.random_choice([2, 64, 1_000])

    def randomize_prefilter(self, rng) -> None:
        """Prefilter knob randomization (ISSUE 17), drawn at the very END
        of the soak's sequence (after randomize_watches) for the
        pinned-seed reason shared by every post-PR-12 satellite: earlier
        cluster-shape and workload-rotation draws must reproduce exactly.
        The knob is drawn both ways so the soak matrix covers on AND off;
        tiny caps force the eviction/decay paths that only forget
        conflicts (the conservative direction the oracle checks)."""
        if rng.coinflip(0.4):
            self.PROXY_CONFLICT_PREFILTER = rng.random_choice([True, False])
        if rng.coinflip(0.25):
            # short prefixes alias unrelated writes into one bucket —
            # blunter summary, still conservative (exact entry confirm)
            self.PREFILTER_PREFIX_LEN = rng.random_choice([1, 3, 6])
        if rng.coinflip(0.25):
            # tiny caps force bucket-entry eviction + wide-list overflow
            self.PREFILTER_BUCKET_ENTRIES = rng.random_choice([2, 8, 32])
            self.PREFILTER_WIDE_RANGES = rng.random_choice([2, 16, 128])
        if rng.coinflip(0.25):
            # tiny bucket cap forces whole-bucket eviction
            self.PREFILTER_MAX_BUCKETS = rng.random_choice([4, 64, 4096])
        if rng.coinflip(0.25):
            # tiny feedback cap forces resolver-side truncation (newest
            # kept; the proxy just learns less — conservative)
            self.PREFILTER_FEEDBACK_MAX_RANGES = rng.random_choice([4, 64, 512])

    def randomize_read_pipeline(self, rng) -> None:
        """Read-pipeline knob randomization, kept OUT of randomize():
        the chaos soak's cluster shapes and workload rotation draw from
        the same stream right after randomize(), so new draws there would
        silently reshuffle every pinned soak seed. The soak calls this at
        the END of its draw sequence instead (tools/soak.py)."""
        if rng.coinflip(0.25):
            # both read paths stay exercised across the soak matrix
            self.CLIENT_READ_COALESCING = rng.random_choice([True, False])
        if rng.coinflip(0.25):
            # tiny batches force the chunking path; tiny depth forces queuing
            self.CLIENT_MULTIGET_MAX_KEYS = rng.random_choice([2, 64, 1024])
        if rng.coinflip(0.25):
            self.CLIENT_READ_PIPELINE_DEPTH = rng.random_choice([1, 2, 8])

    def randomize_commit_path(self, rng) -> None:
        """Commit-path knob randomization (ISSUE 18), drawn at the very
        END of the soak's sequence (after randomize_prefilter) for the
        pinned-seed reason shared by every post-PR-12 satellite: earlier
        cluster-shape and workload-rotation draws must reproduce exactly.
        Each mechanism is drawn both ways so the soak matrix covers the
        legacy paths too — the compiled codec is byte-identical by
        construction, slab settling only regroups wakeups, and the fsync
        pipeline must hold the no-early-ack contract under chaos."""
        if rng.coinflip(0.3):
            self.WIRE_COMPILED_CODEC = rng.random_choice([True, False])
        if rng.coinflip(0.3):
            self.FUTURE_SLAB_SETTLE = rng.random_choice([True, False])
        if rng.coinflip(0.3):
            self.TLOG_FSYNC_PIPELINE = rng.random_choice([True, False])

    def randomize_storage_metrics(self, rng) -> None:
        """Keyspace-telemetry knob randomization (ISSUE 20), drawn at the
        very END of the soak's sequence (after randomize_commit_path) so
        pinned chaos seeds keep their cluster-shape and workload draws
        byte-identical. Sampling is drawn both ways so the soak matrix
        keeps exercising DD's range-scan fallback; the sample factor
        sweeps dense→sparse; history cadence/capacity sweep tiny rings."""
        if rng.coinflip(0.3):
            self.STORAGE_METRICS_SAMPLING = rng.random_choice([True, False])
        if rng.coinflip(0.25):
            self.STORAGE_BYTE_SAMPLE_FACTOR = rng.random_choice([32, 200, 2000])
        if rng.coinflip(0.3):
            self.DD_WAIT_METRICS_SIZING = rng.random_choice([True, False])
        if rng.coinflip(0.25):
            self.METRICS_HISTORY_INTERVAL = rng.random_choice([0.5, 2.0, 10.0])
        if rng.coinflip(0.25):
            self.METRICS_HISTORY_SAMPLES = rng.random_choice([4, 32, 120])
