"""Simulation-only invariant oracles — the analog of fdbrpc/sim_validation.h.

The reference threads debug hooks through production code that are active
only under simulation (debug_advanceMaxCommittedVersion /
debug_checkMinCommittedVersion, sim_validation.h:38): every version ACKED
to a client is recorded, and every recovery's chosen epoch-end version is
checked against it — a recovery that picks an end version below an acked
commit has silently lost durable data, which no workload read would
reliably catch (the key may never be read again).

Wired at the same points as the reference: the proxy's phase-5 ack
(MasterProxyServer.actor.cpp:834 debug_advanceMinCommittedVersion) and the
master's epoch-end determination (masterserver.actor.cpp recovery).
"""

from __future__ import annotations


class DurabilityOracle:
    def __init__(self):
        self.max_acked = 0  # highest commit version acked to ANY client
        self.violations: list[str] = []

    def note_acked(self, version: int) -> None:
        if version > self.max_acked:
            self.max_acked = version

    def forfeit_above(self, version: int) -> None:
        """A forced lossy operation (force_recovery_with_data_loss /
        region failover) explicitly gives up acked commits above
        ``version`` — lower the watermark so later recoveries aren't
        charged with the forfeited tail. The loss is the operation's
        documented contract, not a durability bug."""
        if self.max_acked > version:
            self.max_acked = version

    def check_recovery(self, end_version: int, epoch: int) -> None:
        """A new epoch's end version must cover every acked commit."""
        if end_version < self.max_acked:
            msg = (
                f"recovery epoch {epoch} chose end version {end_version} "
                f"below acked commit {self.max_acked}: acked data LOST"
            )
            self.violations.append(msg)
            raise AssertionError(msg)
