"""Simulation-only invariant oracles — the analog of fdbrpc/sim_validation.h.

The reference threads debug hooks through production code that are active
only under simulation (debug_advanceMaxCommittedVersion /
debug_checkMinCommittedVersion, sim_validation.h:38): every version ACKED
to a client is recorded, and every recovery's chosen epoch-end version is
checked against it — a recovery that picks an end version below an acked
commit has silently lost durable data, which no workload read would
reliably catch (the key may never be read again).

Wired at the same points as the reference: the proxy's phase-5 ack
(MasterProxyServer.actor.cpp:834 debug_advanceMinCommittedVersion) and the
master's epoch-end determination (masterserver.actor.cpp recovery).
"""

from __future__ import annotations


class DurabilityOracle:
    def __init__(self):
        self.max_acked = 0  # highest commit version acked to ANY client
        self.violations: list[str] = []

    def note_acked(self, version: int) -> None:
        if version > self.max_acked:
            self.max_acked = version

    def forfeit_above(self, version: int) -> None:
        """A forced lossy operation (force_recovery_with_data_loss /
        region failover) explicitly gives up acked commits above
        ``version`` — lower the watermark so later recoveries aren't
        charged with the forfeited tail. The loss is the operation's
        documented contract, not a durability bug."""
        if self.max_acked > version:
            self.max_acked = version

    def check_recovery(self, end_version: int, epoch: int) -> None:
        """A new epoch's end version must cover every acked commit."""
        if end_version < self.max_acked:
            msg = (
                f"recovery epoch {epoch} chose end version {end_version} "
                f"below acked commit {self.max_acked}: acked data LOST"
            )
            self.violations.append(msg)
            raise AssertionError(msg)


class PrefilterOracle:
    """Differential oracle for the proxy conflict pre-filter (ISSUE 17).

    The pre-filter's contract is *strictly conservative*: it may miss
    conflicts, but must NEVER reject a transaction the resolver would
    have committed. This oracle proves it: resolvers report every
    committed write range here at the same instant they journal it (so
    this history is a superset of anything any proxy's summary can
    contain — the feedback the proxy learns from is built from the same
    journal entries AFTER this call, and this oracle never forgets), and
    every pre-rejection is re-run against it. A rejection is excused
    only if (1) some read range provably overlaps a committed write at a
    version newer than the read snapshot — the authoritative resolver
    verdict would be CONFLICT — or (2) the snapshot is below the
    resolver's forget horizon — the verdict would be TOO_OLD. Either
    way, never COMMITTED. Anything else is a real bug and fails the sim.
    """

    def __init__(self):
        # lazy import: runtime/ must not import conflict/ at module load
        from ..conflict.oracle import _StepFunction

        self._writes = _StepFunction()
        self.min_floor = 0  # lowest forget horizon any resolver reported
        self.committed_checked = 0
        self.rejections_checked = 0
        self.violations: list[str] = []

    def note_committed(self, version, ranges, oldest_version) -> None:
        for begin, end in ranges:
            self._writes.raise_to(bytes(begin), bytes(end), int(version))
        self.committed_checked += 1
        if oldest_version > self.min_floor:
            self.min_floor = int(oldest_version)

    def check_rejection(self, read_snapshot, read_ranges, proxy="") -> None:
        self.rejections_checked += 1
        for begin, end in read_ranges:
            if self._writes.max_over(bytes(begin), bytes(end)) > read_snapshot:
                return  # genuine conflict: resolver would convict too
        if read_snapshot < self.min_floor:
            return  # resolver would answer TOO_OLD, not COMMITTED
        msg = (
            f"prefilter FALSE REJECTION on proxy {proxy}: snapshot "
            f"{read_snapshot} conflicts with no committed write "
            f"(floor {self.min_floor}) — resolver would have committed"
        )
        self.violations.append(msg)
        raise AssertionError(msg)
