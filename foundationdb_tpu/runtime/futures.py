"""Futures, promises, actors, streams, and combinators on the event loop.

The analog of the reference's flow core (flow/flow.h:275-899 —
SAV/Future/Promise/PromiseStream/NotifiedQueue) and its combinator library
(flow/genericactors.actor.h). Python coroutines replace the C# actor
compiler: ``async def`` bodies are the ``ACTOR`` functions, ``await`` is
``wait()``, and ``spawn()`` drives them as cancellable tasks on the loop.

Semantics mirrored from the reference:
- ``Promise.send`` fires callbacks immediately-but-scheduled (delivery order
  is loop order, deterministic);
- dropping/cancelling an actor's future cancels the actor (Cancelled is
  thrown at its current await point — flow's actor_cancelled);
- ``PromiseStream`` is a multi-value channel; readers block on next().
"""

from __future__ import annotations

from collections import deque
from typing import Any, Awaitable, Callable, Generic, Optional, TypeVar

from . import trace as _trace
from .loop import Cancelled, TaskPriority, current_loop

T = TypeVar("T")


class Future(Generic[T]):
    __slots__ = ("_value", "_error", "_done", "_callbacks", "_task")

    def __init__(self):
        self._value: Optional[T] = None
        self._error: Optional[BaseException] = None
        self._done = False
        self._callbacks: list[Callable[[Future], None]] = []
        self._task: Optional[Task] = None  # set when this is an actor's future

    # -- inspection
    def is_ready(self) -> bool:
        return self._done

    def is_error(self) -> bool:
        return self._done and self._error is not None

    def get(self) -> T:
        assert self._done
        if self._error is not None:
            raise self._error
        return self._value

    # -- completion
    def _set(self, value: T) -> None:
        if self._done:
            return
        self._value = value
        self._done = True
        self._fire()

    def _set_error(self, err: BaseException) -> None:
        if self._done:
            return
        self._error = err
        self._done = True
        self._fire()

    def _fire(self) -> None:
        cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            cb(self)

    def add_callback(self, cb: Callable[["Future"], None]) -> None:
        if self._done:
            cb(self)
        else:
            self._callbacks.append(cb)

    def cancel(self) -> None:
        """Cancel the actor producing this future (no-op if plain promise)."""
        if self._task is not None and not self._done:
            self._task.cancel()

    # -- await protocol
    def __await__(self):
        if not self._done:
            yield self
        if self._error is not None:
            raise self._error
        return self._value


class Promise(Generic[T]):
    __slots__ = ("future",)

    def __init__(self):
        self.future: Future[T] = Future()

    def send(self, value: T = None) -> None:
        self.future._set(value)

    def send_error(self, err: BaseException) -> None:
        self.future._set_error(err)

    def is_set(self) -> bool:
        return self.future.is_ready()


class Task:
    """Drives a coroutine on the loop; the generated actor state machine."""

    def __init__(self, coro, priority: int = TaskPriority.DEFAULT, name: str = None):
        self.coro = coro
        self.future: Future = Future()
        self.future._task = self
        self.priority = priority
        # actor identity for run-loop attribution (runtime/profiler.py):
        # the coroutine's qualname names the async def that IS the actor,
        # threaded through every (re)schedule so the loop can attribute
        # each callback's on-CPU time to its owner. RPC dispatch overrides
        # it with the handler's qualname (the wrapper is anonymous plumbing).
        self.name = (
            name or getattr(coro, "__qualname__", None) or type(coro).__name__
        )
        self._cancelled = False
        self._waiting_on: Optional[Future] = None
        # home loop: every (re)scheduling of this task goes here, NOT to
        # whatever loop is current at wake time — when an old simulation's
        # coroutines are garbage-collected while a new simulation runs,
        # their finalizers must not leak callbacks into the new world
        self.loop = current_loop()
        # trace-span inheritance (runtime/trace.py): a spawned actor runs
        # inside the spawner's active span context; each step saves the
        # (possibly changed) context back so it survives awaits
        self._span_ctx = _trace.active_span()

    def start(self) -> Future:
        self.loop.call_soon(lambda: self._step(None, None), self.priority, self.name)
        return self.future

    def cancel(self) -> None:
        if self.future.is_ready() or self._cancelled:
            return
        self._cancelled = True
        self.loop.call_soon(
            lambda: self._step(None, Cancelled()), TaskPriority.MAX, self.name
        )

    def _step(self, value, error) -> None:
        if self.future.is_ready():
            return
        self._waiting_on = None
        prev_span = _trace.swap_active_span(self._span_ctx)
        try:
            self._step_inner(value, error)
        finally:
            # latch whatever context the body left active (a span opened
            # across this await) and restore the interrupted one
            self._span_ctx = _trace.swap_active_span(prev_span)

    def _step_inner(self, value, error) -> None:
        try:
            if error is not None:
                awaited = self.coro.throw(error)
            else:
                awaited = self.coro.send(value)
        except StopIteration as stop:
            # a cancelled actor never produces a value, even if its body
            # swallowed the Cancelled and returned (flow: actor_cancelled)
            if self._cancelled:
                self.future._set_error(Cancelled())
            else:
                self.future._set(stop.value)
            return
        except Cancelled as c:
            self.future._set_error(c)
            return
        except BaseException as e:
            self.future._set_error(e)
            return
        # The coroutine yielded a Future it waits on.
        assert isinstance(awaited, Future), f"actors must await Futures, got {awaited!r}"
        if self._cancelled:
            # keep re-throwing at every await until the body exits, so an
            # actor that catches Cancelled and awaits again can't hang forever
            self.loop.call_soon(
                lambda: self._step(None, Cancelled()), TaskPriority.MAX, self.name
            )
            return
        self._waiting_on = awaited

        def wake(f: Future, task=self):
            # only the await the task is currently parked on may resume it
            # (a stale pre-cancellation future can fire later); a cancelled
            # task is resumed solely by the Cancelled re-throw in _step
            if (
                task.future.is_ready()
                or task._cancelled
                or task._waiting_on is not f
            ):
                return
            col = task.loop._wake_collector
            if col is not None:
                # settle-slab mode (settle_batch): record the wakeup; the
                # installer reschedules the whole slab in per-priority
                # call_soon_batch entries. All resume-eligibility guards
                # ran above, exactly as on the direct path.
                if f._error is not None:
                    col.append((task, None, f._error))
                else:
                    col.append((task, f._value, None))
                return
            if f._error is not None:
                task.loop.call_soon(
                    lambda: task._step(None, f._error), task.priority, task.name
                )
            else:
                task.loop.call_soon(
                    lambda: task._step(f._value, None), task.priority, task.name
                )

        awaited.add_callback(wake)


def spawn(coro, priority: int = TaskPriority.DEFAULT, name: str = None) -> Future:
    """Run an async def body as an actor; returns its future (cancellable).
    ``name`` overrides the profiler attribution (defaults to the
    coroutine's qualname)."""
    return Task(coro, priority, name).start()


def start_batch(tasks: list) -> None:
    """Start many NOT-yet-started Tasks with ONE loop queue entry (the
    transport's server-side batch dispatch, net/tcp.py): a super-frame of
    N requests drains in a single loop step instead of scheduling N
    wakeups. Each task's first step still runs under its own profiler
    attribution (loop.call_soon_batch); subsequent steps reschedule
    individually as usual. A task cancelled before the batch runs resolves
    through the normal MAX-priority Cancelled re-throw — its batch step
    then no-ops on the ready future."""
    if not tasks:
        return
    if len(tasks) == 1:
        tasks[0].start()
        return
    tasks[0].loop.call_soon_batch(
        [((lambda t=t: t._step(None, None)), t.name) for t in tasks],
        tasks[0].priority,
    )


# slab settling on/off (knob FUTURE_SLAB_SETTLE): off restores the
# one-call_soon-per-wakeup path for A/B runs and chaos coverage
_SLAB_ON = True


def set_slab_settle(on: bool) -> None:
    global _SLAB_ON
    _SLAB_ON = bool(on)


def slab_settle_enabled() -> bool:
    return _SLAB_ON


def settle_batch(settlements: list) -> None:
    """Settle many ``(future, value, error)`` triples in ONE loop step —
    the completion-side mirror of start_batch. A super-frame of N replies
    (net/tcp.py) or a GRV batch fan-out used to pay one call_soon per
    woken waiter task; here every ``_set`` runs under a slab collector
    (loop._wake_collector), the woken tasks are grouped by priority, and
    each priority group resumes via one call_soon_batch entry — per-item
    profiler attribution preserved (BATCH_OWNER discipline).

    Semantics match per-item settling exactly: non-Task callbacks still
    fire synchronously inside ``_set`` (cascaded Task wakeups they cause
    are collected too), wake-eligibility guards run at fire time as
    usual, and priority ordering across groups is the heap's as before.
    With slab settling off (set_slab_settle) this degrades to the plain
    per-item loop."""
    if not settlements:
        return
    if not _SLAB_ON or len(settlements) == 1:
        for fut, value, err in settlements:
            if err is not None:
                fut._set_error(err)
            else:
                fut._set(value)
        return
    loop = current_loop()
    collected: list = []
    prev = loop._wake_collector
    loop._wake_collector = collected
    try:
        for fut, value, err in settlements:
            if err is not None:
                fut._set_error(err)
            else:
                fut._set(value)
    finally:
        loop._wake_collector = prev
    if not collected:
        return
    if len(collected) == 1:
        task, value, err = collected[0]
        loop.call_soon(
            lambda: task._step(value, err), task.priority, task.name
        )
        return
    by_pri: dict = {}
    for item in collected:
        by_pri.setdefault(item[0].priority, []).append(item)
    for pri, items in by_pri.items():
        loop.call_soon_batch(
            [
                ((lambda t=t, v=v, e=e: t._step(v, e)), t.name)
                for t, v, e in items
            ],
            pri,
        )


# ---------------------------------------------------------------------------
# Timers / yields


def delay(seconds: float, priority: int = TaskPriority.DEFAULT) -> Future[None]:
    f: Future[None] = Future()
    current_loop().call_at(current_loop().now() + seconds, lambda: f._set(None), priority)
    return f


def yield_now(priority: int = TaskPriority.DEFAULT) -> Future[None]:
    return delay(0.0, priority)


async def forever():
    await Future()  # never completes (until cancelled)


# ---------------------------------------------------------------------------
# Streams (PromiseStream / NotifiedQueue, flow/flow.h:504-899)


class StreamClosed(Exception):
    pass


class PromiseStream(Generic[T]):
    def __init__(self):
        self._queue: deque[T] = deque()
        self._waiters: deque[Future] = deque()
        self._closed = False
        self._close_error: Optional[BaseException] = None

    def send(self, value: T) -> None:
        if self._closed:
            return
        if self._waiters:
            self._waiters.popleft()._set(value)
        else:
            self._queue.append(value)

    def close(self, err: Optional[BaseException] = None) -> None:
        self._closed = True
        self._close_error = err or StreamClosed()
        while self._waiters:
            self._waiters.popleft()._set_error(self._close_error)

    def next(self) -> Future[T]:
        f: Future[T] = Future()
        if self._queue:
            f._set(self._queue.popleft())
        elif self._closed:
            f._set_error(self._close_error)
        else:
            self._waiters.append(f)
        return f

    def is_empty(self) -> bool:
        return not self._queue


# ---------------------------------------------------------------------------
# Combinators (genericactors.actor.h analogs)


async def wait_for_all(futures: list[Future]) -> list:
    out = []
    for f in futures:
        out.append(await f)
    return out


def wait_for_any(futures: list[Future]) -> Future[int]:
    """Resolves to the index of the first completed future."""
    out: Future[int] = Future()

    def make_cb(i):
        def cb(f: Future):
            if not out.is_ready():
                if f._error is not None:
                    out._set_error(f._error)
                else:
                    out._set(i)

        return cb

    for i, f in enumerate(futures):
        f.add_callback(make_cb(i))
    return out


def settled(fut: Future) -> Future[None]:
    """A future that resolves (never errors) once ``fut`` completes — for
    racing an error-prone future inside wait_for_any without the error
    killing the waiter (flow's ``ready()``). Inspect ``fut`` afterwards."""
    out: Future[None] = Future()
    fut.add_callback(lambda f: out._set(None) if not out.is_ready() else None)
    return out


class TimedOut(Exception):
    pass


class QuorumFailed(Exception):
    """Too many of a quorum's futures failed for it ever to succeed."""


def quorum(futures: list[Future], n: int) -> Future[list]:
    """Resolves once ``n`` of the futures succeed (flow's ``quorum()``),
    with the successful results (order of completion). Errors with
    QuorumFailed as soon as success becomes impossible."""
    out: Future[list] = Future()
    successes: list = []
    fails = [0]
    total = len(futures)
    if n > total:
        out._set_error(QuorumFailed(f"need {n} of {total}"))
        return out

    def cb(f: Future):
        if out.is_ready():
            return
        if f._error is not None:
            fails[0] += 1
            if total - fails[0] < n:
                out._set_error(QuorumFailed(f"{fails[0]}/{total} failed, need {n}"))
        else:
            successes.append(f._value)
            if len(successes) >= n:
                out._set(list(successes))

    for f in futures:
        f.add_callback(cb)
    return out


async def timeout(fut: Future[T], seconds: float, default=None) -> T:
    timer = delay(seconds)
    which = await wait_for_any([fut, timer])
    if which == 0:
        return fut.get()
    fut.cancel()
    return default


class AsyncVar(Generic[T]):
    """A variable whose changes can be awaited (flow's AsyncVar)."""

    def __init__(self, value: T = None):
        self._value = value
        self._change: Future[None] = Future()

    def get(self) -> T:
        return self._value

    def set(self, value: T) -> None:
        if value != self._value:
            self._value = value
            old, self._change = self._change, Future()
            old._set(None)

    def on_change(self) -> Future[None]:
        return self._change


class AsyncTrigger:
    def __init__(self):
        self._f: Future[None] = Future()

    def trigger(self) -> None:
        old, self._f = self._f, Future()
        old._set(None)

    def on_trigger(self) -> Future[None]:
        return self._f


class RequestBatcher:
    """Natural request coalescing: one fetch in flight at a time; callers
    that arrive during a flight form the next batch and share its result.
    The pattern behind GRV batching at both ends (the client's
    readVersionBatcher, fdbclient/NativeAPI.actor.cpp:1290, and the
    proxy's transactionStarter master fetch,
    fdbserver/MasterProxyServer.actor.cpp:925). Joining an *in-flight*
    fetch would break causality (a result observed elsewhere after the
    fetch began could be newer), so only pre-flight arrivals share.

    ``fetch`` is a zero-arg coroutine function; ``spawn`` schedules the
    batcher actor (e.g. ``process.spawn`` or a client's spawn).
    ``counted=True`` calls ``fetch(n)`` with the batch size instead — the
    GRV batcher reports how many transactions share the fetch so proxy
    admission debits per TRANSACTION, not per coalesced request (the
    reference's GetReadVersionRequest.transactionCount)."""

    def __init__(self, fetch, spawn_fn, counted: bool = False):
        self._fetch = fetch
        self._spawn = spawn_fn
        self._counted = counted
        self._waiters: list[Future] = []
        self._running = False

    def join(self) -> Future:
        fut: Future = Future()
        self._waiters.append(fut)
        if not self._running:
            self._running = True
            self._spawn(self._run())
        return fut

    async def _run(self):
        try:
            while self._waiters:
                waiters, self._waiters = self._waiters, []
                try:
                    value = await (
                        self._fetch(len(waiters))
                        if self._counted
                        else self._fetch()
                    )
                except Cancelled:
                    # actor-cancelled-swallow: the batcher dies with its
                    # cancellation, but parked callers must not hang on a
                    # fetch that will never be retried
                    for w in waiters:
                        if not w.is_ready():
                            w._set_error(Cancelled())
                    raise
                except BaseException as e:
                    settle_batch(
                        [(w, None, e) for w in waiters if not w.is_ready()]
                    )
                    continue
                if len(waiters) == 1:
                    # no-hedge single-waiter fast path: resolve the lone
                    # caller's future directly, no slab machinery
                    w = waiters[0]
                    if not w.is_ready():
                        w._set(value)
                else:
                    settle_batch(
                        [(w, value, None) for w in waiters if not w.is_ready()]
                    )
        finally:
            self._running = False


class VersionGate:
    """Orders batch application by (prev_version → version) chaining — the
    sequencing discipline shared by resolvers (Resolver.actor.cpp:104-122)
    and tlogs (tLogCommit version ordering): a batch waits until the gate
    reaches its prev_version, applies, then advances the gate to its own
    version."""

    def __init__(self, version: int = 0):
        self.version = version
        self._waiters: dict[int, Future] = {}  # target version → wakeup

    async def wait_until(self, version: int) -> None:
        while self.version < version:
            f = self._waiters.get(version)
            if f is None:
                f = self._waiters[version] = Future()
            await f

    def advance_to(self, version: int) -> None:
        if version > self.version:
            self.version = version
            for t in [t for t in self._waiters if t <= version]:
                self._waiters.pop(t)._set(None)


class ActorCollection:
    """Holds actor futures; errors propagate, completions are discarded
    (flow/ActorCollection.actor.cpp).

    ``on_error`` (optional) is invoked synchronously with the exception the
    first time an actor dies unhandled — the hook that makes actor death
    LOUD (the reference turns an unhandled error into a TraceEvent + process
    death; silence here once hid a cluster-wide boot failure)."""

    def __init__(self, on_error: Optional[Callable[[BaseException], None]] = None):
        self._actors: list[Future] = []
        self.error: Future = Future()
        self.on_error = on_error

    def add(self, fut: Future) -> None:
        self._actors.append(fut)

        def cb(f: Future):
            # A Cancelled error is benign only if the actor was itself
            # cancelled (cancel_all / explicit cancel). Cancelled *propagated*
            # from awaiting some other cancelled actor is a real failure
            # (the reference's broken_promise) and must surface.
            genuine_cancel = (
                isinstance(f._error, Cancelled)
                and f._task is not None
                and f._task._cancelled
            )
            if f._error is not None and not genuine_cancel:
                if self.on_error is not None:
                    try:
                        self.on_error(f._error)
                    except Exception:
                        pass
                if not self.error.is_ready():
                    self.error._set_error(f._error)
            # prune: completed actors (and their results) must not accumulate
            try:
                self._actors.remove(f)
            except ValueError:
                pass

        fut.add_callback(cb)

    def cancel_all(self) -> None:
        for f in self._actors:
            f.cancel()
        self._actors.clear()
