"""Counters, rates, and latency samples — the analog of flow/Stats.h.

The reference instruments every role with `Counter`s grouped into a
`CounterCollection` traced periodically (flow/Stats.h:55-63 Counter /
:101 CounterCollection; fdbserver/MasterProxyServer.actor.cpp:60
ProxyStats, fdbserver/storageserver.actor.cpp:510 StorageServerMetrics).
This module provides the same three primitives, loop-agnostic (sim or
real time):

- ``Counter``: monotonically growing total with per-interval delta, so a
  trace shows both lifetime totals and current rate.
- ``LatencySample``: bounded reservoir of durations answering p50/p95/p99
  (the reference's LatencyBands / Sample, flow/Stats.h:140).
- ``CounterCollection``: a named group; ``trace_loop()`` emits one trace
  event per interval with every counter's total+rate and every sample's
  percentiles, then resets interval state. ``snapshot()`` returns the
  same data as a dict for the status document (Status.actor.cpp pulls
  role metrics the same way).
"""

from __future__ import annotations

import random
from typing import Optional

from .loop import now
from .trace import SevInfo, trace


class Counter:
    __slots__ = ("name", "value", "_interval_start")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self._interval_start = 0

    def add(self, n: int = 1) -> None:
        self.value += n

    def __iadd__(self, n: int) -> "Counter":
        self.value += n
        return self

    @property
    def interval_delta(self) -> int:
        return self.value - self._interval_start

    def reset_interval(self) -> None:
        self._interval_start = self.value


class LatencySample:
    """Reservoir sample of durations (seconds). Bounded memory; exact
    percentiles while under capacity, uniform reservoir beyond it.

    The reservoir is sorted lazily, at most once per run of reads: every
    percentile/snapshot call reuses one cached sorted buffer until the
    next ``add`` dirties it (a snapshot used to re-sort three times —
    once per percentile — which made a busy status pull O(3·n log n) per
    sample)."""

    __slots__ = ("name", "cap", "count", "_buf", "_rnd", "_sorted")

    def __init__(self, name: str, cap: int = 1024, seed: int = 0):
        self.name = name
        self.cap = cap
        self.count = 0
        self._buf: list[float] = []
        self._rnd = random.Random(seed)
        self._sorted: list[float] = None  # cache; None = dirty

    def add(self, dt: float) -> None:
        self.count += 1
        self._sorted = None
        if len(self._buf) < self.cap:
            self._buf.append(dt)
        else:
            i = self._rnd.randrange(self.count)
            if i < self.cap:
                self._buf[i] = dt

    def _sorted_buf(self) -> list[float]:
        if self._sorted is None:
            self._sorted = sorted(self._buf)
        return self._sorted

    def percentile(self, p: float) -> float:
        if not self._buf:
            return 0.0
        s = self._sorted_buf()
        return s[min(int(len(s) * p), len(s) - 1)]

    def snapshot(self) -> dict:
        # one sort serves all three percentiles
        s = self._sorted_buf()
        n = len(s)

        def pick(p: float) -> float:
            return s[min(int(n * p), n - 1)] if n else 0.0

        return {
            "count": self.count,
            "p50": round(pick(0.5), 6),
            "p95": round(pick(0.95), 6),
            "p99": round(pick(0.99), 6),
        }

    @staticmethod
    def merge(snaps: list) -> dict:
        """Aggregate sample snapshots from many loops/roles: counts sum,
        percentiles take the WORST (a cluster-wide p99 cannot be computed
        from per-role percentiles, but the worst observed band is exactly
        what an operator scanning for starvation wants)."""
        out = {"count": 0, "p50": 0.0, "p95": 0.0, "p99": 0.0}
        for s in snaps:
            if not s:
                continue
            out["count"] += s.get("count") or 0
            for k in ("p50", "p95", "p99"):
                out[k] = max(out[k], s.get(k) or 0.0)
        return out


# default band edges (seconds) — the reference's LatencyBands knob
# thresholds scaled to this system's sim/TCP latency envelope: sub-ms
# fast path through multi-second stalls
DEFAULT_BAND_EDGES = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.1, 0.5, 2.0)


class LatencyBands:
    """Fixed-threshold latency histogram (flow/Stats.h LatencyBands /
    fdbserver's GRV+commit+read latency bands): each request lands in the
    first band whose upper edge covers it, overflow in ``inf``. Unlike the
    reservoir LatencySample this never forgets — band counts are exact
    over the role's lifetime, which is what per-endpoint SLO accounting
    needs."""

    __slots__ = ("name", "edges", "counts", "overflow", "count")

    def __init__(self, name: str, edges: tuple = DEFAULT_BAND_EDGES):
        self.name = name
        self.edges = tuple(edges)
        self.counts = [0] * len(self.edges)
        self.overflow = 0
        self.count = 0

    def add(self, dt: float) -> None:
        self.count += 1
        for i, edge in enumerate(self.edges):
            if dt <= edge:
                self.counts[i] += 1
                return
        self.overflow += 1

    def snapshot(self) -> dict:
        bands = {f"{edge:g}": n for edge, n in zip(self.edges, self.counts)}
        bands["inf"] = self.overflow
        return {"count": self.count, "bands": bands}

    @staticmethod
    def merge(snaps: list) -> dict:
        """Aggregate band snapshots from many roles (the status document
        sums per-endpoint bands cluster-wide)."""
        total = 0
        bands: dict[str, int] = {}
        for s in snaps:
            if not s:
                continue
            total += s.get("count", 0)
            for edge, n in (s.get("bands") or {}).items():
                bands[edge] = bands.get(edge, 0) + n
        return {"count": total, "bands": bands}


class CounterCollection:
    """A role's counters + samples, traced as one periodic event
    (CounterCollection::logToTraceEvent, flow/Stats.cpp)."""

    def __init__(self, name: str, ident: str = ""):
        self.name = name
        self.id = ident
        self.counters: dict[str, Counter] = {}
        self.samples: dict[str, LatencySample] = {}
        self.band_sets: dict[str, LatencyBands] = {}
        self.gauges: dict[str, object] = {}  # name → zero-arg callable
        self._last_trace = None
        self.history = None  # MetricsHistory ring, see ensure_history()

    def counter(self, name: str) -> Counter:
        c = self.counters.get(name)
        if c is None:
            c = self.counters[name] = Counter(name)
        return c

    def latency(self, name: str, cap: int = 1024) -> LatencySample:
        s = self.samples.get(name)
        if s is None:
            s = self.samples[name] = LatencySample(name, cap)
        return s

    def bands(self, name: str, edges: tuple = DEFAULT_BAND_EDGES) -> LatencyBands:
        b = self.band_sets.get(name)
        if b is None:
            b = self.band_sets[name] = LatencyBands(name, edges)
        return b

    def gauge(self, name: str, fn) -> None:
        """Register a zero-arg callable polled at snapshot/trace time
        (the reference's SpecialCounter, flow/Stats.h:121)."""
        self.gauges[name] = fn

    def snapshot(self, elapsed: Optional[float] = None) -> dict:
        out: dict = {"name": self.name, "id": self.id}
        for n, c in self.counters.items():
            out[n] = c.value
            if elapsed and elapsed > 0:
                out[n + "_hz"] = round(c.interval_delta / elapsed, 2)
        for n, s in self.samples.items():
            out[n] = s.snapshot()
        for n, b in self.band_sets.items():
            out[n] = b.snapshot()
        for n, fn in self.gauges.items():
            try:
                out[n] = fn()
            except Exception:
                out[n] = None
        return out

    def trace_now(self, process: str = "") -> dict:
        t = now()
        elapsed = None if self._last_trace is None else t - self._last_trace
        snap = self.snapshot(elapsed)
        self._last_trace = t
        for c in self.counters.values():
            c.reset_interval()
        trace(
            SevInfo,
            f"{self.name}Metrics",
            process,
            ID=self.id,
            Elapsed=round(elapsed, 3) if elapsed is not None else None,
            **{k: v for k, v in snap.items() if k not in ("name", "id")},
        )
        return snap

    async def trace_loop(self, interval: float = 5.0, process: str = ""):
        """Actor: trace this collection every ``interval`` seconds — the
        per-role metrics logger every reference role runs
        (e.g. masterProxyServerCore's traceRole counters)."""
        from .futures import delay

        self._last_trace = now()
        while True:
            await delay(interval)
            self.trace_now(process)

    def ensure_history(self, capacity: int) -> "object":
        """Attach (or resize lazily — capacity changes only apply to a
        fresh ring) the bounded metrics-history ring (ISSUE 20)."""
        if self.history is None:
            from .timeseries import MetricsHistory

            self.history = MetricsHistory(capacity)
        return self.history

    def record_history(self, t: Optional[float] = None) -> None:
        """Snapshot numeric counters/gauges into the history ring now.
        No-op until ensure_history() has been called."""
        if self.history is None:
            return
        self.history.record(now() if t is None else t, self.snapshot())

    async def history_loop(self, knobs):
        """Actor: feed the metrics-history ring at the knob-set cadence
        (METRICS_HISTORY_INTERVAL / METRICS_HISTORY_SAMPLES). Gated on
        METRICS_HISTORY_ENABLED so the overhead-sensitive path can turn
        the whole subsystem off with one knob."""
        from .futures import delay

        if not getattr(knobs, "METRICS_HISTORY_ENABLED", True):
            return
        self.ensure_history(int(knobs.METRICS_HISTORY_SAMPLES))
        interval = float(knobs.METRICS_HISTORY_INTERVAL)
        while True:
            await delay(interval)
            self.record_history()
