"""Transport instrumentation — one CounterCollection per process world.

The analog of the reference's per-connection PacketBuffer/transport
counters (fdbrpc/FlowTransport.actor.cpp's TransportData counters and
the ``Net2Metrics`` frame/byte totals): every world (real TCP or sim)
owns exactly one ``TransportMetrics``; connections and the loopback path
feed it on the hot path, the worker's ``transport.metrics`` endpoint and
the status document's ``transport`` section pull it. The flowlint
registration rule (``transport_metrics_endpoint`` in config.json) keeps
the endpoint from silently disappearing.

Counter semantics:

- ``messagesSent/Received`` — logical RPC messages (requests + replies).
- ``framesSent/Received`` — wire frames; with gen-7 super-frame batching
  one frame carries many messages, so messages/frames is the coalescing
  ratio the bench rows cite.
- ``bytesSent/Received`` — payload + framing bytes on the wire.
- ``loopbackMessages`` vs ``tcpMessages`` — which path carried each
  message (colocated worlds ride the in-process loopback; everything
  else pays the socket).
- ``truncationFaults`` — injected super-frame truncation / partial-flush
  faults observed (sim chaos site + the real-TCP flush fault hook).
- ``messagesPerFlush`` — sample of messages coalesced into each flushed
  super-frame (the pipelining/batching depth evidence).
- ``pipelinedDepth`` — sample of requests already in flight on the
  connection when another was issued (connection-level pipelining).
- ``sendCompactionBytes/recvCompactionBytes`` — bytes moved by buffer
  compaction (the O(n)-copy guarantee the regression test pins).
"""

from __future__ import annotations

from ..runtime.stats import CounterCollection


class TransportMetrics:
    """Per-world transport counters (see module docstring)."""

    def __init__(self, ident: str = ""):
        self.stats = CounterCollection("Transport", ident)
        c = self.stats.counter
        self.messages_sent = c("messagesSent")
        self.messages_received = c("messagesReceived")
        self.frames_sent = c("framesSent")
        self.frames_received = c("framesReceived")
        self.bytes_sent = c("bytesSent")
        self.bytes_received = c("bytesReceived")
        self.loopback_messages = c("loopbackMessages")
        self.tcp_messages = c("tcpMessages")
        self.truncation_faults = c("truncationFaults")
        self.connections = c("connectionsOpened")
        self.connections_closed = c("connectionsClosed")
        self.messages_per_flush = self.stats.latency("messagesPerFlush")
        self.pipelined_depth = self.stats.latency("pipelinedDepth")
        # compaction byte totals are fed by the wire buffers (gauges so the
        # buffers stay dependency-free)
        self._compaction_sources: list = []  # objects with .bytes_moved
        self.stats.gauge("bufferCompactionBytes", self._compaction_bytes)

    def track_buffer(self, buf) -> None:
        """Register a Send/RecvBuffer whose ``bytes_moved`` counts toward
        the compaction gauge (dead connections' buffers are dropped by
        ``untrack_buffer``)."""
        self._compaction_sources.append(buf)

    def untrack_buffer(self, buf) -> None:
        try:
            self._compaction_sources.remove(buf)
        except ValueError:
            pass

    def _compaction_bytes(self) -> int:
        return sum(b.bytes_moved for b in self._compaction_sources)

    def snapshot(self, elapsed=None) -> dict:
        snap = self.stats.snapshot(elapsed)
        sent = snap.get("messagesSent") or 0
        frames = snap.get("framesSent") or 0
        snap["messagesPerFrame"] = round(sent / frames, 2) if frames else 0.0
        return snap
