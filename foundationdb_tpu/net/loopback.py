"""In-process loopback transport for colocated worlds.

The bench box (and every in-process test cluster) runs many ``RealWorld``
processes on one core, often on ONE RealLoop — yet every RPC between them
pays the full localhost TCP tax: connect, frame, CRC, two socket writes,
two selector wakeups. The reference short-circuits same-process traffic
inside FlowTransport (sendLocal — deliver() without ever touching a
connection); this module is that move for colocated *worlds*: a
per-OS-process registry of listening worlds, and a connection object that
carries frames between two of them with zero syscalls.

Semantics parity is deliberate: every message still round-trips through
the wire codec (``wire.encode_value``/``decode_value``), so loopback
peers exchange *copies* — unserializable payloads, schema drift, and
mutation-aliasing bugs surface exactly as they would over a socket.
(That round-trip is also why loopback benefits from the schema-compiled
codec: with WIRE_COMPILED_CODEC on, the per-message encode/decode here
runs the generated whole-struct pack/unpack instead of the per-field
interpretive walk.)
Delivery is scheduled (one ZERO-priority drain per tick per direction,
mirroring the TCP flush tick), so replies never resolve synchronously
and batches arrive as one batch-dispatch — same shape as a gen-7
super-frame landing.

Selection is automatic (``TRANSPORT_LOOPBACK`` knob, on by default):
``RealWorld.request`` consults the registry before dialing. Both worlds
must run on the SAME loop (cross-thread worlds keep using sockets) and
neither may be TLS-configured (a TLS cluster's authentication story must
not be silently bypassed). A closed world leaves the registry, so dead
peers keep their BrokenPromise semantics.
"""

from __future__ import annotations

from typing import Optional

from ..runtime.loop import TaskPriority
from . import wire

# listen address -> world, for THIS OS process only. Worlds register at
# listen time and deregister on close; a re-bound address overwrites.
_REGISTRY: dict[str, object] = {}


def register(world) -> None:
    _REGISTRY[world.node.address] = world


def unregister(world) -> None:
    if _REGISTRY.get(world.node.address) is world:
        del _REGISTRY[world.node.address]


def lookup(address: str) -> Optional[object]:
    return _REGISTRY.get(address)


def connect(world, peer_world) -> "LoopbackConn":
    """Create the conn PAIR between two colocated worlds and install both
    ends in their worlds' routing tables. Returns ``world``'s end."""
    a = LoopbackConn(world, peer_world)
    b = LoopbackConn(peer_world, world)
    a.reverse, b.reverse = b, a
    world._conns[peer_world.node.address] = a
    peer_world._conns[world.node.address] = b
    world.transport_metrics.connections.add(1)
    peer_world.transport_metrics.connections.add(1)
    return a


class LoopbackConn:
    """One direction of a colocated-world connection — duck-types the
    ``_Conn`` surface RealWorld routes through (``peer``/``closed``/
    ``send``/``close``)."""

    __slots__ = ("world", "peer_world", "peer", "closed", "reverse", "_pending", "_drain_scheduled")

    def __init__(self, world, peer_world):
        self.world = world  # the sending side
        self.peer_world = peer_world
        self.peer = peer_world.node.address
        self.closed = False
        self.reverse: Optional["LoopbackConn"] = None
        self._pending: list[bytes] = []  # encoded messages this tick
        self._drain_scheduled = False

    def send(self, msg) -> None:
        if self.closed:
            return
        # encode NOW (wire-format parity: the sender pays for — and
        # observes errors from — serialization exactly like TCP)
        self._pending.append(wire.encode_value(msg))
        m = self.world.transport_metrics
        m.messages_sent.add(1)
        m.loopback_messages.add(1)
        if not self._drain_scheduled:
            self._drain_scheduled = True
            # same coalescing window as the TCP flush tick: everything
            # queued during THIS loop tick arrives as one batch
            self.world.loop.call_soon(self._drain, TaskPriority.ZERO)

    def _drain(self) -> None:
        self._drain_scheduled = False
        if self.closed:
            return
        batch, self._pending = self._pending, []
        if not batch:
            return
        sm = self.world.transport_metrics
        sm.frames_sent.add(1)
        sm.messages_per_flush.add(float(len(batch)))
        rm = self.peer_world.transport_metrics
        rm.frames_received.add(1)
        msgs = []
        for payload in batch:
            sm.bytes_sent.add(len(payload))
            rm.bytes_received.add(len(payload))
            rm.messages_received.add(1)
            rm.loopback_messages.add(1)
            msgs.append(wire.decode_value(payload))
        # deliver as one batch through the receiver's batch-dispatch seam
        # (the same path a super-frame takes off a socket)
        self.peer_world._on_batch(self.reverse, msgs)

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        self._pending.clear()
        self.world.transport_metrics.connections_closed.add(1)
        self.world._conn_closed(self)
        # a loopback conn dies as a pair: the peer observes the disconnect
        # immediately (there is no socket to half-close)
        if self.reverse is not None and not self.reverse.closed:
            self.reverse.close()
