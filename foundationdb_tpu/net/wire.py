"""Wire format: framed, checksummed, self-describing binary messages.

The analog of the reference's packet framing + serialization
(fdbrpc/FlowTransport.actor.cpp packet framing with CRC32C; flow/serialize.h
BinaryWriter/Reader): every TCP message is

    [u32 length][u32 crc32][payload]

and the payload is a tagged binary value tree. Unlike the simulator (which
passes live Python objects — SURVEY.md weak spot: no wire format was
exercised), everything crossing a real process boundary round-trips through
this codec, including the interface dataclasses in server/interfaces.py and
the rich metadata types (KeyRangeMap, ShardMap, LogSystem, Knobs).

Dataclasses and IntEnums register by class name; the registry is seeded
from the interface modules at import. This is a schema-by-convention
format (field order of the dataclass), versioned by the protocol version
in the connection handshake (net/tcp.py) — the same place the reference
pins compatibility (connectPacket protocol version).
"""

from __future__ import annotations

import dataclasses
import enum
import struct
import zlib

from ..runtime.serialize import PROTOCOL_VERSION

_FRAME = struct.Struct("<II")  # length, crc32

# value tags
_NONE, _TRUE, _FALSE = 0, 1, 2
_INT, _FLOAT, _BYTES, _STR = 3, 4, 5, 6
_TUPLE, _LIST, _DICT, _SET, _FROZENSET = 7, 8, 9, 10, 11
_STRUCT, _ENUM = 12, 13

_struct_by_name: dict[str, type] = {}
_enum_by_name: dict[str, type] = {}
_packers: dict[type, tuple[str, callable, callable]] = {}


def register_struct(cls: type) -> type:
    """Register a dataclass for wire transport (by class name)."""
    assert dataclasses.is_dataclass(cls), cls
    _struct_by_name[cls.__name__] = cls
    return cls


def register_enum(cls: type) -> type:
    _enum_by_name[cls.__name__] = cls
    return cls


def register_custom(cls: type, name: str, pack, unpack) -> None:
    """Register a non-dataclass type: pack(obj) -> value tree,
    unpack(value) -> obj."""
    _packers[cls] = (name, pack, unpack)
    _struct_by_name[name] = (pack, unpack)  # marker; resolved in decode


def register_module(mod) -> None:
    """Register every dataclass and IntEnum defined in a module."""
    for name in dir(mod):
        obj = getattr(mod, name)
        if isinstance(obj, type) and obj.__module__ == mod.__name__:
            if dataclasses.is_dataclass(obj):
                register_struct(obj)
            elif issubclass(obj, enum.Enum):
                register_enum(obj)


class WireError(Exception):
    pass


# -- value codec ---------------------------------------------------------------


def _enc(out: list, v) -> None:
    if v is None:
        out.append(bytes([_NONE]))
    elif v is True:
        out.append(bytes([_TRUE]))
    elif v is False:
        out.append(bytes([_FALSE]))
    elif isinstance(v, enum.Enum):
        name = type(v).__name__
        if name not in _enum_by_name:
            raise WireError(f"unregistered enum {type(v)!r}")
        out.append(bytes([_ENUM]))
        _enc_str(out, name)
        _enc(out, v.value)
    elif isinstance(v, int):
        out.append(bytes([_INT]))
        b = v.to_bytes((v.bit_length() + 8) // 8 or 1, "little", signed=True)
        out.append(struct.pack("<B", len(b)))
        out.append(b)
    elif isinstance(v, float):
        out.append(bytes([_FLOAT]))
        out.append(struct.pack("<d", v))
    elif isinstance(v, (bytes, bytearray, memoryview)):
        out.append(bytes([_BYTES]))
        out.append(struct.pack("<I", len(v)))
        out.append(bytes(v))
    elif isinstance(v, str):
        out.append(bytes([_STR]))
        b = v.encode()
        out.append(struct.pack("<I", len(b)))
        out.append(b)
    elif isinstance(v, tuple):
        out.append(bytes([_TUPLE]))
        out.append(struct.pack("<I", len(v)))
        for x in v:
            _enc(out, x)
    elif isinstance(v, list):
        out.append(bytes([_LIST]))
        out.append(struct.pack("<I", len(v)))
        for x in v:
            _enc(out, x)
    elif isinstance(v, dict):
        out.append(bytes([_DICT]))
        out.append(struct.pack("<I", len(v)))
        for k, x in v.items():
            _enc(out, k)
            _enc(out, x)
    elif isinstance(v, frozenset):
        out.append(bytes([_FROZENSET]))
        out.append(struct.pack("<I", len(v)))
        for x in sorted(v, key=repr):
            _enc(out, x)
    elif isinstance(v, set):
        out.append(bytes([_SET]))
        out.append(struct.pack("<I", len(v)))
        for x in sorted(v, key=repr):
            _enc(out, x)
    elif type(v) in _packers:
        name, pack, _unpack = _packers[type(v)]
        out.append(bytes([_STRUCT]))
        _enc_str(out, name)
        _enc(out, pack(v))
    elif dataclasses.is_dataclass(v):
        name = type(v).__name__
        if _struct_by_name.get(name) is not type(v):
            raise WireError(f"unregistered struct {type(v)!r}")
        out.append(bytes([_STRUCT]))
        _enc_str(out, name)
        fields = dataclasses.fields(v)
        _enc(out, tuple(getattr(v, f.name) for f in fields))
    else:
        raise WireError(f"unserializable value {type(v)!r}: {v!r}")


def _enc_str(out: list, s: str) -> None:
    b = s.encode()
    out.append(struct.pack("<H", len(b)))
    out.append(b)


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        v = self.buf[self.pos : self.pos + n]
        if len(v) != n:
            raise WireError("truncated message")
        self.pos += n
        return v

    def u8(self) -> int:
        return self.take(1)[0]

    def u16(self) -> int:
        return struct.unpack("<H", self.take(2))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self.take(4))[0]


def _dec(r: _Reader):
    tag = r.u8()
    if tag == _NONE:
        return None
    if tag == _TRUE:
        return True
    if tag == _FALSE:
        return False
    if tag == _INT:
        n = r.u8()
        return int.from_bytes(r.take(n), "little", signed=True)
    if tag == _FLOAT:
        return struct.unpack("<d", r.take(8))[0]
    if tag == _BYTES:
        return r.take(r.u32())
    if tag == _STR:
        return r.take(r.u32()).decode()
    if tag == _TUPLE:
        return tuple(_dec(r) for _ in range(r.u32()))
    if tag == _LIST:
        return [_dec(r) for _ in range(r.u32())]
    if tag == _DICT:
        n = r.u32()
        return {_dec(r): _dec(r) for _ in range(n)}
    if tag == _SET:
        return {_dec(r) for _ in range(r.u32())}
    if tag == _FROZENSET:
        return frozenset(_dec(r) for _ in range(r.u32()))
    if tag == _ENUM:
        name = r.take(r.u16()).decode()
        cls = _enum_by_name.get(name)
        v = _dec(r)
        if cls is None:
            raise WireError(f"unknown enum {name!r}")
        return cls(v)
    if tag == _STRUCT:
        name = r.take(r.u16()).decode()
        entry = _struct_by_name.get(name)
        v = _dec(r)
        if entry is None:
            raise WireError(f"unknown struct {name!r}")
        if isinstance(entry, tuple):
            _pack, unpack = entry
            return unpack(v)
        return entry(*v)
    raise WireError(f"bad tag {tag}")


def encode_value(v) -> bytes:
    out: list = []
    _enc(out, v)
    return b"".join(out)


def decode_value(buf: bytes):
    r = _Reader(buf)
    v = _dec(r)
    if r.pos != len(buf):
        raise WireError("trailing bytes in message")
    return v


# -- frames --------------------------------------------------------------------


def encode_frame(payload: bytes) -> bytes:
    return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload


def decode_frames(buf: bytearray):
    """Consume complete frames from ``buf`` (mutates it); yields payloads.
    Raises WireError on a checksum mismatch (connection must drop)."""
    out = []
    pos = 0
    while len(buf) - pos >= _FRAME.size:
        length, crc = _FRAME.unpack_from(buf, pos)
        if length > 1 << 30:
            raise WireError(f"oversized frame {length}")
        if len(buf) - pos - _FRAME.size < length:
            break
        payload = bytes(buf[pos + _FRAME.size : pos + _FRAME.size + length])
        if zlib.crc32(payload) != crc:
            raise WireError("frame checksum mismatch")
        out.append(payload)
        pos += _FRAME.size + length
    del buf[:pos]
    return out


def handshake_bytes(listen_addr: str) -> bytes:
    """Connection preamble: protocol version + the dialer's listen address
    (the reference's connectPacket)."""
    b = listen_addr.encode()
    return struct.pack("<QH", PROTOCOL_VERSION, len(b)) + b


def parse_handshake(buf: bytearray):
    """Returns (listen_addr, consumed) or None if incomplete."""
    if len(buf) < 10:
        return None
    ver, n = struct.unpack_from("<QH", buf, 0)
    if ver != PROTOCOL_VERSION:
        raise WireError(f"protocol version mismatch: {ver:#x}")
    if len(buf) < 10 + n:
        return None
    addr = bytes(buf[10 : 10 + n]).decode()
    return addr, 10 + n


# -- registry seeding ----------------------------------------------------------


def _seed_registry() -> None:
    from ..server import interfaces, log_system, coordination, master
    from ..kv import mutations
    from ..runtime import locality

    # every dataclass a role can hand to request()/CoordinatedState.write()
    # must be here — DBCoreState travels to coordinators over real TCP
    for mod in (interfaces, log_system, coordination, master, mutations, locality):
        register_module(mod)

    from ..kv.keyrange_map import KeyRangeMap

    register_custom(
        KeyRangeMap,
        "KeyRangeMap",
        lambda m: list(m.ranges()),
        lambda rs: _keyrange_map_from(rs),
    )

    from ..server.proxy import ShardMap

    register_custom(
        ShardMap,
        "ShardMap",
        lambda s: s.to_list(),
        lambda rs: ShardMap.from_list(rs),
    )

    from ..server.log_system import LogSystem

    register_custom(
        LogSystem,
        "LogSystem",
        lambda ls: ls.tlog_set,
        lambda ts: LogSystem(ts),
    )

    from ..runtime.knobs import Knobs

    register_custom(
        Knobs,
        "Knobs",
        lambda k: k.as_dict(),
        lambda d: Knobs(**d),
    )


def _keyrange_map_from(ranges):
    from ..kv.keyrange_map import KeyRangeMap

    m = KeyRangeMap()
    for b, e, v in ranges:
        m.insert(b, e, v)
    return m


_seed_registry()
