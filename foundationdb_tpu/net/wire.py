"""Wire format: framed, checksummed, self-describing binary messages.

The analog of the reference's packet framing + serialization
(fdbrpc/FlowTransport.actor.cpp packet framing with CRC32C; flow/serialize.h
BinaryWriter/Reader): every TCP message is

    [u32 length][u32 crc32][payload]

and the payload is a tagged binary value tree. Unlike the simulator (which
passes live Python objects — SURVEY.md weak spot: no wire format was
exercised), everything crossing a real process boundary round-trips through
this codec, including the interface dataclasses in server/interfaces.py and
the rich metadata types (KeyRangeMap, ShardMap, LogSystem, Knobs).

Dataclasses and IntEnums register by class name; the registry is seeded
from the interface modules at import. This is a schema-by-convention
format (field order of the dataclass), versioned by the protocol version
in the connection handshake (net/tcp.py) — the same place the reference
pins compatibility (connectPacket protocol version).
"""

from __future__ import annotations

import dataclasses
import enum
import struct
import zlib
from typing import Optional

from ..runtime.serialize import PROTOCOL_VERSION

_FRAME = struct.Struct("<II")  # length, crc32

# value tags
_NONE, _TRUE, _FALSE = 0, 1, 2
_INT, _FLOAT, _BYTES, _STR = 3, 4, 5, 6
_TUPLE, _LIST, _DICT, _SET, _FROZENSET = 7, 8, 9, 10, 11
_STRUCT, _ENUM = 12, 13

_struct_by_name: dict[str, type] = {}
_enum_by_name: dict[str, type] = {}
_packers: dict[type, tuple[str, callable, callable]] = {}


def register_struct(cls: type) -> type:
    """Register a dataclass for wire transport (by class name). Also
    code-gens the schema-compiled encoder/decoder pair for the class
    (see the compiled-codec section below) — registration IS the schema
    compilation step, so a class can never be reachable on the wire
    without a matching compiled codec."""
    assert dataclasses.is_dataclass(cls), cls
    _struct_by_name[cls.__name__] = cls
    _compile_struct_codec(cls)
    return cls


def register_enum(cls: type) -> type:
    _enum_by_name[cls.__name__] = cls
    return cls


def register_custom(cls: type, name: str, pack, unpack) -> None:
    """Register a non-dataclass type: pack(obj) -> value tree,
    unpack(value) -> obj."""
    _packers[cls] = (name, pack, unpack)
    _struct_by_name[name] = (pack, unpack)  # marker; resolved in decode


def register_module(mod) -> None:
    """Register every dataclass and IntEnum defined in a module."""
    for name in dir(mod):
        obj = getattr(mod, name)
        if isinstance(obj, type) and obj.__module__ == mod.__name__:
            if dataclasses.is_dataclass(obj):
                register_struct(obj)
            elif issubclass(obj, enum.Enum):
                register_enum(obj)


class WireError(Exception):
    pass


# -- value codec ---------------------------------------------------------------
#
# Hot path: messages are encoded/decoded once per RPC on every process,
# and profiling the real-TCP cluster put the naive isinstance-chain
# encoder at ~40% of client CPU. The format is UNCHANGED; the encoder
# dispatches on exact type (one dict hit for the common concrete types),
# caches per-int encodings for small ints, and precomputes each struct
# class's header bytes + field getter.

_B_NONE, _B_TRUE, _B_FALSE = bytes([_NONE]), bytes([_TRUE]), bytes([_FALSE])
_B_INT, _B_FLOAT, _B_BYTES = bytes([_INT]), bytes([_FLOAT]), bytes([_BYTES])
_B_STR, _B_TUPLE, _B_LIST = bytes([_STR]), bytes([_TUPLE]), bytes([_LIST])
_B_DICT, _B_SET, _B_FROZENSET = bytes([_DICT]), bytes([_SET]), bytes([_FROZENSET])
_B_STRUCT, _B_ENUM = bytes([_STRUCT]), bytes([_ENUM])
_U32 = struct.Struct("<I").pack
_F64 = struct.Struct("<d").pack


def _int_bytes(v: int) -> bytes:
    b = v.to_bytes((v.bit_length() + 8) // 8 or 1, "little", signed=True)
    return _B_INT + bytes([len(b)]) + b


_SMALL_INTS = [_int_bytes(v) for v in range(-128, 4096)]


def _enc_int(out, v):
    if -128 <= v < 4096:
        out.append(_SMALL_INTS[v + 128])
    else:
        out.append(_int_bytes(v))


def _enc_float(out, v):
    out.append(_B_FLOAT)
    out.append(_F64(v))


def _enc_bytes(out, v):
    out.append(_B_BYTES)
    out.append(_U32(len(v)))
    out.append(bytes(v))


# memoized whole encodings for short, recurring strings: message kinds
# ("req"/"ok"/"err"), endpoint tokens, and role uids repeat on every RPC —
# bounded cache so adversarial/unbounded string sets cannot grow it
_STR_CACHE: dict = {}


def _enc_str_v(out, v):
    enc = _STR_CACHE.get(v)
    if enc is not None:
        out.append(enc)
        return
    b = v.encode()
    if len(b) <= 64 and len(_STR_CACHE) < 4096:
        _STR_CACHE[v] = enc = _B_STR + _U32(len(b)) + b
        out.append(enc)
        return
    out.append(_B_STR)
    out.append(_U32(len(b)))
    out.append(b)


def _enc_tuple(out, v):
    out.append(_B_TUPLE)
    out.append(_U32(len(v)))
    for x in v:
        _enc(out, x)


def _enc_list(out, v):
    out.append(_B_LIST)
    out.append(_U32(len(v)))
    for x in v:
        _enc(out, x)


def _enc_dict(out, v):
    out.append(_B_DICT)
    out.append(_U32(len(v)))
    for k, x in v.items():
        _enc(out, k)
        _enc(out, x)


def _enc_set(out, v):
    out.append(_B_SET)
    out.append(_U32(len(v)))
    for x in sorted(v, key=repr):
        _enc(out, x)


def _enc_frozenset(out, v):
    out.append(_B_FROZENSET)
    out.append(_U32(len(v)))
    for x in sorted(v, key=repr):
        _enc(out, x)


_ENC_DISPATCH = {
    type(None): lambda out, v: out.append(_B_NONE),
    bool: lambda out, v: out.append(_B_TRUE if v else _B_FALSE),
    int: _enc_int,
    float: _enc_float,
    bytes: _enc_bytes,
    bytearray: _enc_bytes,
    memoryview: _enc_bytes,
    str: _enc_str_v,
    tuple: _enc_tuple,
    list: _enc_list,
    dict: _enc_dict,
    set: _enc_set,
    frozenset: _enc_frozenset,
}

def _struct_header(name: str) -> bytes:
    b = name.encode()
    return _B_STRUCT + struct.pack("<H", len(b)) + b


def _enc(out: list, v) -> None:
    f = _ENC_DISPATCH.get(type(v))
    if f is None:
        f = _resolve_encoder(type(v))
    f(out, v)


def _resolve_encoder(cls: type):
    """First sighting of a type outside the concrete-type table: build its
    encoder, REGISTER it in the dispatch table, return it. Registered
    dataclasses get a precomputed header + attrgetter; enums memoize the
    full per-member bytes (members are singletons)."""
    import operator

    if issubclass(cls, enum.Enum):
        name = cls.__name__
        if name not in _enum_by_name:
            raise WireError(f"unregistered enum {cls!r}")
        b = name.encode()
        pre = _B_ENUM + struct.pack("<H", len(b)) + b
        member_cache: dict = {}

        def f(out, v, _pre=pre, _cache=member_cache):
            enc = _cache.get(v)
            if enc is None:
                tmp = [_pre]
                _enc(tmp, v.value)
                enc = _cache[v] = b"".join(tmp)
            out.append(enc)

    elif cls in _packers:
        name, pack, _unpack = _packers[cls]
        header = _struct_header(name)

        def f(out, v, _h=header, _pack=pack):
            out.append(_h)
            _enc(out, _pack(v))

    elif dataclasses.is_dataclass(cls):
        name = cls.__name__
        if _struct_by_name.get(name) is not cls:
            raise WireError(f"unregistered struct {cls!r}")
        fields = [fl.name for fl in dataclasses.fields(cls)]
        if not fields:
            getter = lambda obj: ()  # noqa: E731
        elif len(fields) == 1:
            one = operator.attrgetter(fields[0])
            getter = lambda obj, _g=one: (_g(obj),)  # noqa: E731
        else:
            getter = operator.attrgetter(*fields)
        header = _struct_header(name)

        def f(out, v, _h=header, _g=getter):
            out.append(_h)
            _enc_tuple(out, _g(v))

        # prefer the schema-compiled encoder when the codec is on; the
        # interpretive closure above remains the set_compiled_codec(False)
        # path (the dispatch entry is evicted on toggle and re-resolved)
        if _COMPILED_ON:
            comp = _COMPILED_ENC.get(cls)
            if comp is not None:
                f = comp

    # subclasses of the concrete containers/scalars (NamedTuples, int
    # subclasses that are not IntEnum, ...) encode as their base type —
    # the format has no tag for them
    elif issubclass(cls, bool):
        f = _ENC_DISPATCH[bool]
    elif issubclass(cls, int):
        def f(out, v):
            _enc_int(out, int(v))
    elif issubclass(cls, (bytes, bytearray, memoryview)):
        f = _enc_bytes
    elif issubclass(cls, str):
        def f(out, v):
            _enc_str_v(out, str(v))
    elif issubclass(cls, tuple):
        f = _enc_tuple
    elif issubclass(cls, list):
        f = _enc_list
    elif issubclass(cls, dict):
        f = _enc_dict
    elif issubclass(cls, frozenset):
        f = _enc_frozenset
    elif issubclass(cls, set):
        f = _enc_set
    else:
        raise WireError(f"unserializable value {cls!r}")
    _ENC_DISPATCH[cls] = f
    return f


def _enc_str(out: list, s: str) -> None:
    b = s.encode()
    out.append(struct.pack("<H", len(b)))
    out.append(b)


class _Reader:
    __slots__ = ("buf", "pos", "_mv")

    def __init__(self, buf):
        # memoryview input = zero-copy decode straight out of the receive
        # ring (net/tcp.py): only leaf byte values are materialized (they
        # must own their bytes — decoded messages outlive the buffer)
        self.buf = buf
        self.pos = 0
        self._mv = isinstance(buf, memoryview)

    def take(self, n: int) -> bytes:
        v = self.buf[self.pos : self.pos + n]
        if len(v) != n:
            raise WireError("truncated message")
        self.pos += n
        return bytes(v) if self._mv else v

    def u8(self) -> int:
        v = self.buf[self.pos]
        self.pos += 1
        return v

    def u16(self) -> int:
        v = struct.unpack_from("<H", self.buf, self.pos)[0]
        self.pos += 2
        return v

    def u32(self) -> int:
        v = struct.unpack_from("<I", self.buf, self.pos)[0]
        self.pos += 4
        return v


_U32_UNPACK_FROM = struct.Struct("<I").unpack_from
_F64_UNPACK_FROM = struct.Struct("<d").unpack_from


def _dec_int(r):
    # direct-slice read (no take() call / no leaf copy): int.from_bytes
    # accepts memoryview slices; frames are CRC-verified before decode,
    # and decode_value's end-position check catches overruns
    buf = r.buf
    pos = r.pos
    n = buf[pos]
    end = pos + 1 + n
    r.pos = end
    return int.from_bytes(buf[pos + 1 : end], "little", signed=True)


def _dec_float(r):
    v = _F64_UNPACK_FROM(r.buf, r.pos)[0]
    r.pos += 8
    return v


def _dec_bytes(r):
    buf = r.buf
    pos = r.pos
    (n,) = _U32_UNPACK_FROM(buf, pos)
    end = pos + 4 + n
    v = buf[pos + 4 : end]
    if len(v) != n:
        raise WireError("truncated message")
    r.pos = end
    return bytes(v) if r._mv else v


def _dec_str(r):
    buf = r.buf
    pos = r.pos
    (n,) = _U32_UNPACK_FROM(buf, pos)
    end = pos + 4 + n
    v = buf[pos + 4 : end]
    if len(v) != n:
        raise WireError("truncated message")
    r.pos = end
    return str(v, "utf-8")


def _dec_enum(r):
    name = r.take(r.u16()).decode()
    cls = _enum_by_name.get(name)
    v = _dec(r)
    if cls is None:
        raise WireError(f"unknown enum {name!r}")
    return cls(v)


def _dec_struct(r):
    name = r.take(r.u16()).decode()
    if _COMPILED_ON:
        dec = _COMPILED_DEC.get(name)
        if dec is not None:
            return dec(r)
    entry = _struct_by_name.get(name)
    v = _dec(r)
    if entry is None:
        raise WireError(f"unknown struct {name!r}")
    if isinstance(entry, tuple):
        _pack, unpack = entry
        return unpack(v)
    return entry(*v)


_DEC_DISPATCH = [
    lambda r: None,  # _NONE
    lambda r: True,  # _TRUE
    lambda r: False,  # _FALSE
    _dec_int,  # _INT
    _dec_float,  # _FLOAT
    _dec_bytes,  # _BYTES
    _dec_str,  # _STR
    lambda r: tuple([_dec(r) for _ in range(r.u32())]),  # _TUPLE
    lambda r: [_dec(r) for _ in range(r.u32())],  # _LIST
    lambda r: {_dec(r): _dec(r) for _ in range(r.u32())},  # _DICT
    lambda r: {_dec(r) for _ in range(r.u32())},  # _SET
    lambda r: frozenset([_dec(r) for _ in range(r.u32())]),  # _FROZENSET
    _dec_struct,  # _STRUCT
    _dec_enum,  # _ENUM
]


def _dec(r: _Reader):
    buf = r.buf
    pos = r.pos
    tag = buf[pos]
    r.pos = pos + 1
    if tag >= len(_DEC_DISPATCH):
        raise WireError(f"bad tag {tag}")
    return _DEC_DISPATCH[tag](r)


_OUT_FREE: list = []


def encode_value(v) -> bytes:
    # chunk-list reuse: encoding is synchronous (no awaits anywhere under
    # _enc), so a small free pool of chunk lists is only ever touched
    # between top-level encodes; an encode that raises abandons its list
    out: list = _OUT_FREE.pop() if _OUT_FREE else []
    _enc(out, v)
    b = b"".join(out)
    out.clear()
    if len(_OUT_FREE) < 8:
        _OUT_FREE.append(out)
    return b


def decode_value(buf):
    r = _Reader(buf)
    try:
        v = _dec(r)
    except (IndexError, struct.error):
        # direct-slice readers surface truncation as index/struct errors;
        # normalize so connections drop with WireError like any bad frame
        raise WireError("truncated message")
    if r.pos != len(buf):
        raise WireError("trailing bytes in message")
    return v


# -- schema-compiled codec -----------------------------------------------------
#
# Second-generation hot path. The dispatch codec above still walks one
# Python frame per field of every struct (_enc_tuple -> _enc -> dict hit
# per field). Registered dataclasses ARE the schema (field order by
# convention), so register_struct() code-gens one specialized encode and
# one specialized decode function per class:
#
#   * the struct header, tuple tag and field count collapse into a single
#     precomputed prefix constant (one append instead of four);
#   * scalar fields (int/bytes/str/None/bool/float) inline their tag
#     handling behind EXACT-class guards (``x.__class__ is int``);
#   * anything that fails a guard — a subclass, a container, a nested
#     struct, an enum — falls through to the generic _enc/_dec walk.
#
# The fallback rule is what makes byte-identity with the interpretive
# codec structural rather than aspirational: every inline fast path is a
# transcription of the matching _ENC_DISPATCH/_DEC_DISPATCH entry, and
# everything else bottoms out in literally the same helpers. The wire
# format is UNCHANGED (gen-9, no protocol bump); tests/test_wire_codec.py
# proves identity by fuzzed differential plus a golden-bytes fixture.
#
# set_compiled_codec(False) (knob WIRE_COMPILED_CODEC) restores the
# interpretive path for A/B runs and the differential harness.

_COMPILED_ON = True
_COMPILED_ENC: dict = {}  # cls -> enc(out, v)
_COMPILED_DEC: dict = {}  # class name -> dec(reader)
_COMPILED_META: dict = {}  # class name -> (cls, field-name tuple)

_ENC_FIELD_TMPL = """\
    x = v.{fname}
    t = x.__class__
    if t is int:
        if -128 <= x < 4096:
            ap(_si[x + 128])
        else:
            ap(_ib(x))
    elif t is bytes:
        ap(_bb)
        ap(_u32(len(x)))
        ap(x)
    elif t is str:
        _es(out, x)
    elif t is _NT:
        ap(_bn)
    elif t is bool:
        ap(_bt if x else _bf)
    elif t is float:
        ap(_bfl)
        ap(_f64(x))
    else:
        _e(out, x)
"""

_DEC_FIELD_TMPL = """\
    tag = buf[pos]
    if tag == 3:
        ln = buf[pos + 1]
        end = pos + 2 + ln
        {f} = int.from_bytes(buf[pos + 2 : end], "little", signed=True)
        pos = end
    elif tag == 5:
        (ln,) = _u32f(buf, pos + 1)
        end = pos + 5 + ln
        x = buf[pos + 5 : end]
        if len(x) != ln:
            raise _we("truncated message")
        {f} = bytes(x) if mv else x
        pos = end
    elif tag == 6:
        (ln,) = _u32f(buf, pos + 1)
        end = pos + 5 + ln
        x = buf[pos + 5 : end]
        if len(x) != ln:
            raise _we("truncated message")
        {f} = str(x, "utf-8")
        pos = end
    elif tag == 0:
        {f} = None
        pos += 1
    elif tag == 1:
        {f} = True
        pos += 1
    elif tag == 2:
        {f} = False
        pos += 1
    else:
        r.pos = pos
        {f} = _d(r)
        pos = r.pos
"""


def _compile_struct_codec(cls: type) -> None:
    """Code-gen the specialized encoder/decoder pair for a registered
    dataclass and record the schema it was generated from (codec_audit
    checks the recorded field tuple against the live class)."""
    name = cls.__name__
    fields = tuple(fl.name for fl in dataclasses.fields(cls))
    pre = _struct_header(name) + _B_TUPLE + _U32(len(fields))

    src = ["def enc(out, v):", "    ap = out.append", "    ap(_pre)"]
    for fname in fields:
        src.append(_ENC_FIELD_TMPL.format(fname=fname))
    ens = {
        "_pre": pre,
        "_si": _SMALL_INTS,
        "_ib": _int_bytes,
        "_bb": _B_BYTES,
        "_u32": _U32,
        "_es": _enc_str_v,
        "_NT": type(None),
        "_bn": _B_NONE,
        "_bt": _B_TRUE,
        "_bf": _B_FALSE,
        "_bfl": _B_FLOAT,
        "_f64": _F64,
        "_e": _enc,
    }
    exec("\n".join(src), ens)

    fvars = [f"f{i}" for i in range(len(fields))]
    src = [
        "def dec(r):",
        "    buf = r.buf",
        "    pos = r.pos",
        # a payload that is not a tuple of exactly our arity (schema drift
        # from a same-version peer, or a hand-built message) takes the
        # generic walk — same constructor call, same errors
        "    if buf[pos] != 7:",
        "        return _cls(*_d(r))",
        "    (n,) = _u32f(buf, pos + 1)",
        f"    if n != {len(fields)}:",
        "        return _cls(*_d(r))",
        "    pos += 5",
        "    mv = r._mv",
    ]
    for f in fvars:
        src.append(_DEC_FIELD_TMPL.format(f=f))
    src.append("    r.pos = pos")
    src.append(f"    return _cls({', '.join(fvars)})")
    dns = {
        "_cls": cls,
        "_d": _dec,
        "_u32f": _U32_UNPACK_FROM,
        "_we": WireError,
    }
    exec("\n".join(src), dns)

    _COMPILED_ENC[cls] = ens["enc"]
    _COMPILED_DEC[name] = dns["dec"]
    _COMPILED_META[name] = (cls, fields)
    # a re-registered (reloaded) class must not keep serving a previously
    # resolved encoder
    _ENC_DISPATCH.pop(cls, None)


def set_compiled_codec(on: bool) -> None:
    """Select the compiled (True) or interpretive (False) struct codec.
    Evicts resolved dataclass encoders so _resolve_encoder re-binds under
    the new mode; decode consults the flag per struct header."""
    global _COMPILED_ON
    on = bool(on)
    if on == _COMPILED_ON:
        return
    _COMPILED_ON = on
    for cls in list(_COMPILED_ENC):
        _ENC_DISPATCH.pop(cls, None)


def compiled_codec_enabled() -> bool:
    return _COMPILED_ON


def codec_audit() -> list:
    """Staleness gate over the compiled codec (the collection-audit
    analog of flowlint's role_required_counters): every register_struct
    dataclass must have a compiled encoder/decoder generated from the
    class's CURRENT field list. Returns a list of problem strings —
    empty means clean. Catches registry pokes that bypass
    register_struct and field drift after generation."""
    problems = []
    for name, entry in sorted(_struct_by_name.items()):
        if isinstance(entry, tuple):
            continue  # register_custom: hand-written pack/unpack pair
        meta = _COMPILED_META.get(name)
        if meta is None:
            problems.append(f"{name}: registered struct has no compiled codec")
            continue
        cls, fields = meta
        if cls is not entry:
            problems.append(f"{name}: compiled codec bound to a stale class")
            continue
        current = tuple(fl.name for fl in dataclasses.fields(entry))
        if current != fields:
            problems.append(
                f"{name}: fields drifted since codec generation "
                f"({list(fields)} -> {list(current)}) — re-register to re-gen"
            )
            continue
        if entry not in _COMPILED_ENC or name not in _COMPILED_DEC:
            problems.append(f"{name}: compiled encoder/decoder missing")
    return problems


# -- frames --------------------------------------------------------------------
#
# Two wire framings share the stream (gen-7):
#
#   legacy frame      [u32 length][u32 crc32][payload]
#   super-frame       [u32 entries_len | BATCH_BIT][u32 crc32][u32 count]
#                     then count x ([u32 len][payload])
#
# The high bit of the length word marks a super-frame (legacy lengths are
# capped at 2^30, so the bit is unambiguous). A super-frame carries every
# message a connection coalesced in one loop tick — ONE frame header, ONE
# checksum, ONE receive-side dispatch for the whole batch. The CRC covers
# the entries region. Receivers accept both framings unconditionally;
# the TRANSPORT_FRAME_BATCHING knob only selects what a sender EMITS, so
# the gen-6-shaped path stays available for A/B within one build.

_BATCH_BIT = 0x8000_0000
_SUPER = struct.Struct("<III")  # entries_len|BATCH_BIT, crc32, count
_U32_AT = struct.Struct("<I").unpack_from


def encode_frame(payload: bytes) -> bytes:
    return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload


def encode_super_frame(payloads: list) -> list:
    """One super-frame as an iovec-style buffer list
    (``[header, len0, p0, len1, p1, ...]``) — built for vectored
    ``socket.sendmsg`` flushes, so the coalesced messages are never
    copied into a joined buffer on the fast path."""
    iov = [b""]
    elen = 0
    crc = 0
    for p in payloads:
        pfx = _U32(len(p))
        crc = zlib.crc32(p, zlib.crc32(pfx, crc))
        iov.append(pfx)
        iov.append(p)
        elen += 4 + len(p)
    if elen >= 1 << 30:
        raise WireError(f"oversized super-frame {elen}")
    iov[0] = _SUPER.pack(elen | _BATCH_BIT, crc, len(payloads))
    return iov


def decode_frames(buf: bytearray):
    """Consume complete frames from ``buf`` (mutates it); yields payloads.
    Raises WireError on a checksum mismatch (connection must drop)."""
    out = []
    pos = 0
    while len(buf) - pos >= _FRAME.size:
        length, crc = _FRAME.unpack_from(buf, pos)
        if length > 1 << 30:
            raise WireError(f"oversized frame {length}")
        if len(buf) - pos - _FRAME.size < length:
            break
        payload = bytes(buf[pos + _FRAME.size : pos + _FRAME.size + length])
        if zlib.crc32(payload) != crc:
            raise WireError("frame checksum mismatch")
        out.append(payload)
        pos += _FRAME.size + length
    del buf[:pos]
    return out


def parse_frames(rb: "RecvBuffer"):
    """Parse complete frames (legacy AND super) out of a receive buffer.
    Returns ``(payload_views, consumed, n_frames)`` where the views point
    INTO the buffer — decode them before calling ``rb.consume(consumed)``
    (consumption may compact the underlying storage)."""
    out = []
    frames = 0
    mv = rb.view()
    n = len(mv)
    pos = 0
    while n - pos >= _FRAME.size:
        length, crc = _FRAME.unpack_from(mv, pos)
        if length & _BATCH_BIT:
            elen = length & ~_BATCH_BIT
            if elen >= 1 << 30:
                raise WireError(f"oversized super-frame {elen}")
            if n - pos < _SUPER.size or n - pos - _SUPER.size < elen:
                break  # incomplete — wait for more bytes
            (count,) = _U32_AT(mv, pos + 8)
            entries = mv[pos + _SUPER.size : pos + _SUPER.size + elen]
            if zlib.crc32(entries) != crc:
                raise WireError("super-frame checksum mismatch")
            epos = 0
            for _ in range(count):
                if elen - epos < 4:
                    raise WireError("super-frame entry truncated")
                (plen,) = _U32_AT(entries, epos)
                if elen - epos - 4 < plen:
                    raise WireError("super-frame entry truncated")
                out.append(entries[epos + 4 : epos + 4 + plen])
                epos += 4 + plen
            if epos != elen:
                raise WireError("trailing bytes in super-frame")
            frames += 1
            pos += _SUPER.size + elen
        else:
            if length > 1 << 30:
                raise WireError(f"oversized frame {length}")
            if n - pos - _FRAME.size < length:
                break
            payload = mv[pos + _FRAME.size : pos + _FRAME.size + length]
            if zlib.crc32(payload) != crc:
                raise WireError("frame checksum mismatch")
            out.append(payload)
            frames += 1
            pos += _FRAME.size + length
    return out, pos, frames


# -- transport buffers ---------------------------------------------------------


class RecvBuffer:
    """Preallocated receive buffer: ``recv_into`` lands bytes directly in
    place, frames are parsed as zero-copy ``memoryview`` slices, and
    consumed space is reclaimed by watermark-triggered compaction — total
    copying over a connection's life is O(bytes received), not the
    O(n²) of per-message ``bytes +=`` / ``del buf[:n]`` churn.
    ``bytes_moved`` counts every byte compaction relocates (the
    regression test's accounting)."""

    __slots__ = ("_buf", "_pos", "_end", "watermark", "bytes_moved")

    def __init__(self, size: int = 1 << 16, watermark: int = 1 << 16):
        self._buf = bytearray(max(int(size), 4096))
        self._pos = 0  # consumed offset
        self._end = 0  # filled offset
        self.watermark = max(int(watermark), 1)
        self.bytes_moved = 0

    def __len__(self) -> int:
        return self._end - self._pos

    def writable(self, need: int = 1 << 16) -> memoryview:
        """A view of free tail space (at least ``need`` bytes): compacts
        first if the live region is offset, grows (doubles) only when the
        live bytes genuinely exceed capacity."""
        if len(self._buf) - self._end < need:
            if self._pos:
                self._compact()
            while len(self._buf) - self._end < need:
                self._buf.extend(bytes(len(self._buf)))
        return memoryview(self._buf)[self._end :]

    def commit(self, n: int) -> None:
        """Bytes were written into ``writable()`` space."""
        self._end += n

    def feed(self, data) -> None:
        """Copy-in path for tests and non-socket sources."""
        mv = self.writable(len(data))
        mv[: len(data)] = data
        del mv  # release the export before any later resize
        self._end += len(data)

    def view(self) -> memoryview:
        return memoryview(self._buf)[self._pos : self._end]

    def consume(self, n: int) -> None:
        self._pos += n
        if self._pos == self._end:
            self._pos = self._end = 0  # free reset — nothing to move
        elif self._pos >= self.watermark and self._pos >= self._end - self._pos:
            # compact only once the dead prefix outweighs the live bytes:
            # each surviving byte can then be moved O(1) times amortized
            # (dead-prefix-only watermarks re-move a large live tail per
            # small consume — the quadratic shape this class exists to kill)
            self._compact()

    def _compact(self) -> None:
        live = self._end - self._pos
        self._buf[0:live] = self._buf[self._pos : self._end]
        self.bytes_moved += live
        self._pos, self._end = 0, live


class SendBuffer:
    """Outbound byte queue with a consumed offset instead of per-send
    ``del buf[:n]``: partial sends advance the offset (O(1)), and the dead
    prefix is reclaimed in one move once it crosses the watermark —
    amortized O(1) per byte regardless of how the kernel fragments the
    sends. ``bytes_moved`` accounts compaction work."""

    __slots__ = ("_buf", "_pos", "watermark", "bytes_moved")

    def __init__(self, watermark: int = 1 << 16):
        self._buf = bytearray()
        self._pos = 0
        self.watermark = max(int(watermark), 1)
        self.bytes_moved = 0

    def __len__(self) -> int:
        return len(self._buf) - self._pos

    def append(self, data) -> None:
        self._buf += data

    def peek(self) -> memoryview:
        return memoryview(self._buf)[self._pos :]

    def consume(self, n: int) -> None:
        self._pos += n
        live = len(self._buf) - self._pos
        if not live:
            self._buf.clear()
            self._pos = 0
        elif self._pos >= self.watermark and self._pos >= live:
            # same amortization rule as RecvBuffer: reclaim only when the
            # dead prefix outweighs the live bytes
            del self._buf[: self._pos]
            self.bytes_moved += live
            self._pos = 0


def pack_span_context(ctx) -> Optional[tuple]:
    """Span context → wire shape (None when the caller is unsampled).
    The envelope field the real-TCP request tuple carries — the analog of
    FlowTransport's SpanContextMessage ahead of the request packet."""
    if ctx is None:
        return None
    return (ctx.trace_id, ctx.span_id)


def unpack_span_context(v):
    """Wire shape → SpanContext (tolerates None / malformed: tracing must
    never turn a valid request into an error)."""
    if not isinstance(v, (tuple, list)) or len(v) != 2:
        return None
    from ..runtime.trace import SpanContext

    return SpanContext(str(v[0]), str(v[1]))


def handshake_bytes(listen_addr: str) -> bytes:
    """Connection preamble: protocol version + the dialer's listen address
    (the reference's connectPacket)."""
    b = listen_addr.encode()
    return struct.pack("<QH", PROTOCOL_VERSION, len(b)) + b


def parse_handshake(buf: bytearray):
    """Returns (listen_addr, consumed) or None if incomplete."""
    if len(buf) < 10:
        return None
    ver, n = struct.unpack_from("<QH", buf, 0)
    if ver != PROTOCOL_VERSION:
        raise WireError(f"protocol version mismatch: {ver:#x}")
    if len(buf) < 10 + n:
        return None
    addr = bytes(buf[10 : 10 + n]).decode()
    return addr, 10 + n


# -- registry seeding ----------------------------------------------------------


def _seed_registry() -> None:
    from ..server import interfaces, log_system, coordination, master
    from ..kv import mutations
    from ..runtime import locality

    # every dataclass a role can hand to request()/CoordinatedState.write()
    # must be here — DBCoreState travels to coordinators over real TCP
    for mod in (interfaces, log_system, coordination, master, mutations, locality):
        register_module(mod)

    from ..kv.keyrange_map import KeyRangeMap

    register_custom(
        KeyRangeMap,
        "KeyRangeMap",
        lambda m: list(m.ranges()),
        lambda rs: _keyrange_map_from(rs),
    )

    from ..server.proxy import ShardMap

    register_custom(
        ShardMap,
        "ShardMap",
        lambda s: s.to_list(),
        lambda rs: ShardMap.from_list(rs),
    )

    from ..server.log_system import LogSystem

    register_custom(
        LogSystem,
        "LogSystem",
        lambda ls: ls.tlog_set,
        lambda ts: LogSystem(ts),
    )

    from ..runtime.knobs import Knobs

    register_custom(
        Knobs,
        "Knobs",
        lambda k: k.as_dict(),
        lambda d: Knobs(**d),
    )


def _keyrange_map_from(ranges):
    from ..kv.keyrange_map import KeyRangeMap

    m = KeyRangeMap()
    for b, e, v in ranges:
        m.insert(b, e, v)
    return m


_seed_registry()
