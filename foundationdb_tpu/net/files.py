"""Async file I/O: the durability substrate, virtualized for simulation.

The analog of fdbrpc/IAsyncFile.h with its two personalities:

- ``SimFile`` — the simulator's file (AsyncFileNonDurable.actor.h): writes
  land in an unsynced overlay with modeled latency; ``sync()`` promotes
  them to durable content; a process kill DROPS (or partially applies —
  the corruption model of :460-505) everything unsynced. Files live in
  the machine's ``SimDisk`` and survive reboot, which is exactly what
  makes restart tests meaningful.
- ``RealFile`` — plain OS files (AsyncFileEIO's job); used outside the
  simulator (benchmarks, the native engine's siblings).

Only whole-value page semantics are needed by the engines here (DiskQueue
pages, snapshot blobs), so the API is a minimal subset: read / write /
truncate / sync / size.
"""

from __future__ import annotations

import os

from ..runtime.futures import delay


class DiskFault(IOError):
    """An injected io_error or disk-full (flow/FaultInjection.h:26,
    sim2.actor.cpp:676 SimDiskSpace). Surfaces from SimFile ops; the
    owning role treats it like any fatal disk error (role death →
    recovery replaces it)."""


class SimDisk:
    """All files of one simulated machine; survives process reboot."""

    def __init__(self, sim, machine: str):
        self.sim = sim
        self.machine = machine
        self.files: dict[str, "SimFile"] = {}
        # fault injection (machine-scoped, like the reference's per-
        # machine io_error injection): probability an op raises, and an
        # optional capacity that makes writes past it fail as disk-full
        self.io_error_p = 0.0
        self.capacity: int = None

    def open(self, path: str) -> "SimFile":
        f = self.files.get(path)
        if f is None:
            f = self.files[path] = SimFile(self.sim, path, disk=self)
        return f

    def total_bytes(self) -> int:
        return sum(f.size() for f in self.files.values())

    def inject_io_errors(self, p: float) -> None:
        """Arm (p > 0) or disarm per-op io_error injection."""
        self.io_error_p = p

    def set_capacity(self, capacity) -> None:
        """None = unlimited; otherwise writes that would grow the disk
        past ``capacity`` bytes raise disk-full."""
        self.capacity = capacity

    def _maybe_fault(self, grew: int = 0) -> None:
        if (
            self.io_error_p > 0.0
            and self.sim.loop.random.coinflip(self.io_error_p)
        ):
            raise DiskFault(f"injected io_error on {self.machine}")
        if (
            grew > 0
            and self.capacity is not None
            and self.total_bytes() + grew > self.capacity
        ):
            raise DiskFault(f"disk full on {self.machine}")

    def exists(self, path: str) -> bool:
        return path in self.files

    def list(self) -> list[str]:
        return sorted(self.files)

    def remove(self, path: str) -> None:
        self.files.pop(path, None)

    def on_kill(self) -> None:
        """Machine kill: unsynced writes are lost — and, buggify-style,
        a random prefix of them may have reached the platter
        (AsyncFileNonDurable:460-505's KILLED mode)."""
        rng = self.sim.loop.random
        for f in self.files.values():
            f.lose_unsynced(rng)


class SimFile:
    SYNC_TIME = 0.0005  # defaults; knobs SIM_FILE_SYNC_TIME/_WRITE_TIME
    WRITE_TIME = 0.00005

    def _sync_time(self):
        k = getattr(self.sim, "knobs", None)
        return getattr(k, "SIM_FILE_SYNC_TIME", self.SYNC_TIME)

    def _write_time(self):
        k = getattr(self.sim, "knobs", None)
        return getattr(k, "SIM_FILE_WRITE_TIME", self.WRITE_TIME)

    def __init__(self, sim, path: str, disk: "SimDisk" = None):
        self.sim = sim
        self.path = path
        self.disk = disk
        self._durable = bytearray()
        # unsynced ops in ISSUE ORDER: ("write", offset, bytes) |
        # ("trunc", size). One ordered list, replayed in sequence, so a
        # truncate never retroactively clips a write issued after it and a
        # write issued before a truncate never resurrects bytes beyond it —
        # orderings a real disk can't produce.
        self._pending_ops: list[tuple] = []

    # -- IAsyncFile ------------------------------------------------------------

    def _fault(self, grew: int = 0) -> None:
        if self.disk is not None:
            self.disk._maybe_fault(grew)

    async def write(self, offset: int, data: bytes) -> None:
        await delay(self._write_time())
        if self.disk is not None and self.disk.capacity is not None:
            # size() replays every pending op — only pay for it when a
            # disk-full window is actually armed
            self._fault(grew=max(0, offset + len(data) - self.size()))
        else:
            self._fault()
        self._pending_ops.append(("write", offset, bytes(data)))

    async def read(self, offset: int, length: int) -> bytes:
        await delay(self._write_time())
        self._fault()
        img = self._image()
        return bytes(img[offset : offset + length])

    async def sync(self) -> None:
        await delay(self._sync_time())
        self._fault()
        self._durable = self._image()
        self._pending_ops = []

    async def truncate(self, size: int) -> None:
        await delay(self._write_time())
        self._fault()
        self._pending_ops.append(("trunc", size))

    def size(self) -> int:
        return len(self._image())

    # -- sim internals ---------------------------------------------------------

    def _image(self) -> bytearray:
        img = bytearray(self._durable)
        for op in self._pending_ops:
            if op[0] == "write":
                _, offset, data = op
                if len(img) < offset:
                    img.extend(b"\x00" * (offset - len(img)))
                img[offset : offset + len(data)] = data
            else:
                del img[op[1] :]
        return img

    def lose_unsynced(self, rng) -> None:
        """Kill semantics: each unsynced op independently may or may not
        have hit the disk (the nondurable file's page-wise coinflip),
        replayed in issue order so surviving ops keep their sequencing."""
        self._pending_ops = [op for op in self._pending_ops if rng.coinflip(0.5)]
        self._durable = self._image()
        self._pending_ops = []


class RealDisk:
    """OS directory as a disk (for benches and the native engine path)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def open(self, path: str) -> "RealFile":
        return RealFile(os.path.join(self.root, path))

    def exists(self, path: str) -> bool:
        return os.path.exists(os.path.join(self.root, path))

    def list(self) -> list[str]:
        return sorted(os.listdir(self.root))

    def remove(self, path: str) -> None:
        p = os.path.join(self.root, path)
        if os.path.exists(p):
            os.unlink(p)


class RealFile:
    def __init__(self, path: str):
        self.path = path
        flags = os.O_RDWR | os.O_CREAT
        self._fd = os.open(path, flags, 0o644)

    async def write(self, offset: int, data: bytes) -> None:
        os.pwrite(self._fd, data, offset)

    async def read(self, offset: int, length: int) -> bytes:
        return os.pread(self._fd, length, offset)

    async def sync(self) -> None:
        os.fsync(self._fd)

    async def truncate(self, size: int) -> None:
        os.ftruncate(self._fd, size)

    def size(self) -> int:
        return os.fstat(self._fd).st_size

    def close(self) -> None:
        os.close(self._fd)
