"""Deterministic cluster simulation: processes, network, faults.

The analog of the reference's Sim2 (fdbrpc/sim2.actor.cpp:720 — virtual
time, per-process scheduling, connection clogging/latency, kill/reboot) built
on the runtime event loop. Every "process" is a container of actors with an
address; messages between processes are scheduled with seeded random latency;
fault APIs mirror ISimulator (fdbrpc/simulator.h:148-155,263):

  clog_pair(a, b, secs)   — delay all a→b traffic
  partition(a, b)/heal()  — drop a↔b traffic
  kill_process / reboot   — cancel all actors of a process (optionally
                            rerunning its boot function)

Determinism: latency and loss draw from the loop's DeterministicRandom; a
whole cluster run replays bit-identically from its seed (§4 of SURVEY.md —
the primary correctness strategy).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from ..runtime.futures import ActorCollection, Cancelled, Future, spawn
from ..runtime.locality import Locality
from ..runtime.buggify import buggify
from ..runtime.knobs import Knobs
from ..runtime.loop import EventLoop, TaskPriority, set_loop
from ..runtime.trace import SevError, SevInfo, SevWarn, trace


class BrokenPromise(Exception):
    """Request to a dead/unknown endpoint (flow's broken_promise)."""


class TransportTruncated(BrokenPromise):
    """A transport fault ate this request (the sim's super-frame
    truncation / partial-flush site — ISSUE 14's chaos satellite).
    Subclassing BrokenPromise makes it retryable through every existing
    failure path (loadbalance rotation, commit_unknown handling) while
    staying distinctly typed: per-request degradation, never a wedged
    connection."""


class Endpoint:
    """(process address, token) — fdbrpc/FlowTransport.h:28-49."""

    __slots__ = ("address", "token")

    def __init__(self, address: str, token: str):
        self.address = address
        self.token = token

    def __repr__(self):
        return f"{self.address}:{self.token}"


class SimProcess:
    def __init__(self, sim: "Sim", address: str, machine: str, boot=None, locality=None):
        self.sim = sim
        self.address = address
        self.machine = machine
        self.locality = locality or Locality.of(machine)
        self.boot = boot  # async fn(process) rerun on reboot
        self.endpoints: dict[str, Callable] = {}  # token → async handler
        self.actors = ActorCollection(on_error=self._on_actor_error)
        self.alive = True
        self.reboots = 0

    def _on_actor_error(self, err: BaseException) -> None:
        """Unhandled actor death is LOUD: SevError with traceback (the
        reference's unhandled-error → TraceEvent("...Error") + death path,
        flow/ActorCollection.actor.cpp). BrokenPromise is routine in sim
        (requests racing kills), and propagated Cancelled is its moral
        equivalent (awaiting a cancelled sibling) — warn, don't scream."""
        import traceback as _tb

        sev = SevWarn if isinstance(err, (BrokenPromise, Cancelled)) else SevError
        trace(
            sev,
            "UnhandledActorError",
            self.address,
            Err=repr(err),
            Backtrace="".join(
                _tb.format_exception(type(err), err, err.__traceback__)
            )[-2000:],
        )

    def register(self, token: str, handler: Callable) -> Endpoint:
        self.endpoints[token] = handler
        return Endpoint(self.address, token)

    def spawn(
        self, coro, priority: int = TaskPriority.DEFAULT, name: str = None
    ) -> Future:
        fut = spawn(coro, priority, name)
        self.actors.add(fut)
        return fut

    def request(self, ep: "Endpoint", payload: Any) -> Future:
        """RPC originating from this process (its address is the source)."""
        return self.sim.request(self.address, ep, payload)


class Sim:
    """One simulated cluster world bound to one event loop."""

    def __init__(
        self, seed: int = 0, knobs: Optional[Knobs] = None, chaos: bool = False
    ):
        self.loop = EventLoop(seed)
        self.knobs = knobs or Knobs()
        # run-loop profiler, SIM personality: deterministic per-actor step
        # counters + virtual starvation samples; no wall-dependent trace
        # events (SlowTask is the real personality's)
        from ..runtime import profiler as _profiler

        _profiler.install(self.loop, knobs=self.knobs, wall=False, ident="sim")
        # chaos=True arms BUGGIFY sites (flow/flow.h:60) with this sim's
        # seeded rng; activate() installs it so concurrent test sims
        # cannot cross-contaminate
        from ..runtime.buggify import Buggify

        self.buggify = Buggify(self.loop.random.fork() if chaos else None)
        self.processes: dict[str, SimProcess] = {}
        self.disks: dict[str, Any] = {}  # machine → SimDisk (survives reboot)
        self._clogged_until: dict[tuple[str, str], float] = {}
        self._partitioned: set[tuple[str, str]] = set()
        # simulation-only durability oracle (fdbrpc/sim_validation.h:38):
        # acked commit versions vs recovery end versions
        from ..runtime.validation import DurabilityOracle, PrefilterOracle

        self.validation = DurabilityOracle()
        # differential oracle for the proxy conflict pre-filter
        # (ISSUE 17): every pre-rejection re-proven conservative
        self.prefilter_oracle = PrefilterOracle()
        # transport counters (net/metrics.py) — parity with RealWorld so
        # the worker's transport.metrics endpoint answers on both
        # personalities (sim has no frames; messages count per delivery)
        from .metrics import TransportMetrics

        self.transport_metrics = TransportMetrics("sim")
        # modeled wire frames: sends to the same destination within one
        # event-loop tick would share a super-frame on the real transport
        # (gen-7 frame batching), so they share one modeled frame here —
        # makes messagesPerFrame meaningful on sim benches (the watch-storm
        # "one super-frame per connection" evidence) without a real wire.
        # {(src, dst) → (tick, messages_in_open_frame)}
        self._open_frames: dict = {}
        # transport chaos (ISSUE 14): armed EXPLICITLY with a dedicated
        # rng (tools/soak.py draws it at the very END of its sequence) so
        # the main chaos stream — and every pinned seed riding it — stays
        # byte-identical whether or not faults are armed
        self._transport_fault_rng = None
        self._transport_fault_p = 0.0
        self._transport_fault_windows = None

    def arm_transport_faults(self, rng, p: float = 0.01, windows=None) -> None:
        """Arm the super-frame truncation fault site: while armed, each
        delivery is independently eaten with probability ``p``, failing
        THAT request's reply with the typed retryable TransportTruncated
        (the observable semantics of a torn super-frame on the real
        transport: the lost tail's requests fail, everything else
        proceeds). ``windows`` bounds the chaos to [(t0, t1), ...] sim-time
        episodes — like every duration-bounded fault workload (clogging,
        disk failure): sustained per-message loss on RECOVERY-critical
        RPCs keeps the commit epoch in a permanent recovery storm, which
        is an unreachable regime for a real torn flush (the connection
        re-establishes). None = always on (unit tests)."""
        self._transport_fault_rng = rng
        self._transport_fault_p = p
        self._transport_fault_windows = list(windows) if windows else None

    def _transport_fault_fires(self) -> bool:
        if self._transport_fault_rng is None:
            return False
        if self._transport_fault_windows is not None:
            t = self.loop.now()
            if not any(t0 <= t < t1 for t0, t1 in self._transport_fault_windows):
                return False
        return self._transport_fault_rng.coinflip(self._transport_fault_p)

    def disk(self, machine: str):
        """The machine's persistent SimDisk (files survive kill/reboot)."""
        d = self.disks.get(machine)
        if d is None:
            from .files import SimDisk

            d = self.disks[machine] = SimDisk(self, machine)
        return d

    # -- world construction ---------------------------------------------------

    def new_process(
        self, address: str, machine: str = None, boot=None, zone: str = None,
        dc: str = "dc0",
    ) -> SimProcess:
        machine = machine or address
        loc = Locality.of(machine, zone=zone, dc=dc)
        p = SimProcess(self, address, machine, boot, locality=loc)
        self.processes[address] = p
        if boot is not None:
            p.spawn(boot(p))
        return p

    def kill_zone(self, zone: str) -> list[str]:
        """Kill every process in a failure domain (the simulator's
        machine/zone kill, fdbrpc/simulator.h:148 KillType)."""
        killed = []
        for addr, p in list(self.processes.items()):
            if p.alive and p.locality.zone == zone:
                self.kill_process(addr)
                killed.append(addr)
        return killed

    # -- messaging ------------------------------------------------------------

    def _latency(self) -> float:
        if buggify():
            return self.knobs.SIM_MAX_LATENCY * 10  # network hiccup
        # Sim2's networkLatency shape (sim2.actor.cpp:1618, Knobs.cpp:106):
        # almost always MIN + FAST·a (~0.5 ms average), with a rare long
        # tail up to SIM_MAX_LATENCY — not uniform; a uniform draw put the
        # AVERAGE hop at (MIN+MAX)/2 and tripled the commit budget
        k = self.knobs
        a = self.loop.random.random01()
        p_fast = 0.999
        if a <= p_fast:
            return k.SIM_MIN_LATENCY + k.SIM_FAST_LATENCY / p_fast * a
        a = (a - p_fast) / (1 - p_fast)
        return k.SIM_MIN_LATENCY + k.SIM_MAX_LATENCY * a

    def _deliverable(self, src: str, dst: str) -> bool:
        return (src, dst) not in self._partitioned and (
            dst,
            src,
        ) not in self._partitioned

    def _delivery_time(self, src: str, dst: str) -> float:
        t = self.loop.now() + self._latency()
        clog = self._clogged_until.get((src, dst), 0.0)
        return max(t, clog)

    def request(self, src: str, ep: Endpoint, payload: Any) -> Future:
        """One RPC: request and reply each traverse the simulated network.
        The reply future errors with BrokenPromise if the destination is dead
        or unreachable — callers retry exactly like the reference's clients.

        The caller's active span context rides the envelope (the analog of
        FlowTransport attaching the span to the packet header): the handler
        actor is spawned under it, so server-side spans become children of
        the client's without any request dataclass carrying trace fields."""
        from ..runtime import trace as _trace

        span_ctx = _trace.active_span()
        reply: Future = Future()
        self._count_send(src, ep.address)
        if self._transport_fault_fires():
            # transport-truncate chaos site: this request rode the torn
            # tail of a super-frame — typed retryable failure for THIS
            # caller only, delivered with reply latency like any error
            from ..runtime.buggify import mark_fired

            mark_fired(("transport", "transport-truncate"))
            self.transport_metrics.truncation_faults.add(1)
            self._reply_err(ep.address, src, reply, TransportTruncated(str(ep)))
            return reply

        def deliver():
            self.transport_metrics.messages_received.add(1)
            dst = self.processes.get(ep.address)
            if dst is None or not dst.alive or ep.token not in dst.endpoints:
                # reply travels dst→src
                self._reply_err(ep.address, src, reply, BrokenPromise(str(ep)))
                return
            handler = dst.endpoints[ep.token]

            async def run_and_reply():
                try:
                    # the handler runs inline in this actor (owned by the
                    # destination process, so kill_process cancels it
                    # mid-flight); routine request errors are relayed to the
                    # caller and must NOT latch the process's actor-failure
                    # channel, hence no separate spawn
                    result = await handler(payload)
                except Cancelled:
                    self._reply_err(ep.address, src, reply, BrokenPromise(str(ep)))
                    return
                except BaseException as e:
                    self._reply_err(ep.address, src, reply, e)
                    return
                self._reply_ok(ep.address, src, reply, result)

            prev = _trace.swap_active_span(span_ctx)
            try:
                # run-loop attribution: the dispatch wrapper is anonymous
                # plumbing — name the task after the HANDLER so profiler
                # output reads "StorageServer.get_value", not run_and_reply
                dst.spawn(
                    run_and_reply(),
                    name=getattr(handler, "__qualname__", None),
                )
            finally:
                _trace.swap_active_span(prev)

        if not self._deliverable(src, ep.address):
            # dropped on the floor: the caller's timeout/failure monitor acts
            return reply
        self.loop.call_at(self._delivery_time(src, ep.address), deliver)
        return reply

    def _count_send(self, src: str, dst: str) -> None:
        """Message + modeled-frame accounting for one sim send. Same-tick
        sends to the same destination share one frame when frame batching
        is on (what the real transport's flush coalescing does to a
        fan-out burst); each flush's depth feeds messagesPerFlush."""
        m = self.transport_metrics
        m.messages_sent.add(1)
        if not getattr(self.knobs, "TRANSPORT_FRAME_BATCHING", True):
            m.frames_sent.add(1)
            m.frames_received.add(1)
            m.messages_per_flush.add(1.0)
            return
        t = self.loop.now()
        key = (src, dst)
        open_frame = self._open_frames.get(key)
        if open_frame is not None and open_frame[0] == t:
            self._open_frames[key] = (t, open_frame[1] + 1)
            return
        if open_frame is not None:
            m.messages_per_flush.add(float(open_frame[1]))
        if len(self._open_frames) > 4096:
            # stale open frames from dead pairs: flush everything not on
            # the current tick (their frames were already counted)
            for k, (tk, n) in list(self._open_frames.items()):
                if tk != t:
                    m.messages_per_flush.add(float(n))
                    del self._open_frames[k]
        self._open_frames[key] = (t, 1)
        m.frames_sent.add(1)
        m.frames_received.add(1)

    def _reply_ok(self, src: str, dst: str, reply: Future, value) -> None:
        if not self._deliverable(src, dst):
            return
        self._count_send(src, dst)
        self.loop.call_at(
            self._delivery_time(src, dst),
            lambda: (
                self.transport_metrics.messages_received.add(1),
                reply._set(value),
            ),
        )

    def _reply_err(self, src: str, dst: str, reply: Future, err) -> None:
        if not self._deliverable(src, dst):
            return
        self._count_send(src, dst)
        self.loop.call_at(
            self._delivery_time(src, dst),
            lambda: (
                self.transport_metrics.messages_received.add(1),
                reply._set_error(err),
            ),
        )

    # -- fault injection (ISimulator analog) ----------------------------------

    def clog_pair(self, a: str, b: str, seconds: float) -> None:
        until = self.loop.now() + seconds
        self._clogged_until[(a, b)] = max(self._clogged_until.get((a, b), 0), until)
        self._clogged_until[(b, a)] = max(self._clogged_until.get((b, a), 0), until)
        trace(SevInfo, "Clog", "sim", A=a, B=b, Until=until)

    def partition(self, a: str, b: str) -> None:
        self._partitioned.add((a, b))
        trace(SevWarn, "Partition", "sim", A=a, B=b)

    def heal(self, a: str = None, b: str = None) -> None:
        if a is None:
            self._partitioned.clear()
        else:
            self._partitioned.discard((a, b))
            self._partitioned.discard((b, a))

    def kill_process(self, address: str, reboot_in: Optional[float] = None) -> None:
        p = self.processes.get(address)
        if p is None or not p.alive:
            return
        trace(SevWarn, "KillProcess", address, RebootIn=reboot_in)
        p.alive = False
        p.actors.cancel_all()
        p.endpoints.clear()
        disk = self.disks.get(p.machine)
        if disk is not None:
            disk.on_kill()  # unsynced writes lost (AsyncFileNonDurable)
        if reboot_in is not None and p.boot is not None:
            self.loop.call_at(self.loop.now() + reboot_in, lambda: self.reboot(address))

    def reboot(self, address: str) -> None:
        p = self.processes.get(address)
        if p is None or p.alive or p.boot is None:
            return
        trace(SevInfo, "RebootProcess", address)
        p.alive = True
        p.reboots += 1
        p.actors = ActorCollection(on_error=p._on_actor_error)
        p.spawn(p.boot(p))

    # -- running --------------------------------------------------------------

    def activate(self) -> None:
        set_loop(self.loop)
        from ..runtime.buggify import set_buggify

        set_buggify(self.buggify)

    def run(self, until: float = float("inf"), stop_when=None) -> float:
        self.activate()
        return self.loop.run(until, stop_when)

    def run_until_done(self, fut: Future, limit: float = 1e9) -> Any:
        self.activate()
        self.loop.run(until=limit, stop_when=fut.is_ready)
        if not fut.is_ready():
            raise TimeoutError(f"simulation did not finish by t={limit}")
        return fut.get()
