"""Minimal HTTP/1.1: framing, async client, two transports.

The analog of fdbrpc/HTTP.actor.cpp (doRequest framing over a
connection) in the shape this codebase needs: Content-Length framing
only (no chunked encoding — the blob tier controls both ends), an async
client whose transport is pluggable:

- ``SimHttpTransport``: the whole HTTP byte stream round-trips through a
  simulated process endpoint, so blob traffic gets the simulator's
  latency/partition/kill model for free while the framing code is the
  REAL one under test.
- ``RealHttpTransport``: one non-blocking TCP connection per request on
  the RealLoop (connect → write → read-to-completion), for talking to an
  actual blob server over the wire.
"""

from __future__ import annotations

from typing import Optional

from ..runtime.futures import Future


class HttpError(Exception):
    pass


def encode_request(
    method: str, path: str, body: bytes = b"", headers: dict = None
) -> bytes:
    h = {"Content-Length": str(len(body)), "Connection": "close"}
    h.update(headers or {})
    lines = [f"{method} {path} HTTP/1.1"]
    lines += [f"{k}: {v}" for k, v in h.items()]
    return ("\r\n".join(lines) + "\r\n\r\n").encode() + body


def encode_response(status: int, body: bytes = b"", headers: dict = None) -> bytes:
    reason = {200: "OK", 204: "No Content", 404: "Not Found",
              400: "Bad Request", 500: "Internal Server Error"}.get(status, "?")
    h = {"Content-Length": str(len(body))}
    h.update(headers or {})
    lines = [f"HTTP/1.1 {status} {reason}"]
    lines += [f"{k}: {v}" for k, v in h.items()]
    return ("\r\n".join(lines) + "\r\n\r\n").encode() + body


def parse_message(raw: bytes):
    """(start_line, headers dict, body) — or None if incomplete."""
    split = raw.find(b"\r\n\r\n")
    if split < 0:
        return None
    head = raw[:split].decode("latin-1").split("\r\n")
    headers = {}
    for line in head[1:]:
        k, _, v = line.partition(":")
        headers[k.strip().lower()] = v.strip()
    n = int(headers.get("content-length", "0"))
    body = raw[split + 4 : split + 4 + n]
    if len(body) < n:
        return None
    return head[0], headers, body


def parse_request(raw: bytes):
    """(method, path, headers, body) or None if incomplete."""
    msg = parse_message(raw)
    if msg is None:
        return None
    start, headers, body = msg
    parts = start.split(" ")
    if len(parts) < 3:
        raise HttpError(f"bad request line {start!r}")
    return parts[0], parts[1], headers, body


def parse_response(raw: bytes):
    """(status, headers, body) or None if incomplete."""
    msg = parse_message(raw)
    if msg is None:
        return None
    start, headers, body = msg
    parts = start.split(" ")
    if len(parts) < 2 or not parts[0].startswith("HTTP/"):
        raise HttpError(f"bad status line {start!r}")
    return int(parts[1]), headers, body


class SimHttpTransport:
    """Requests ride the simulator's network as one message each; the
    server side (blobstore.mount_sim) parses and answers the same bytes
    a real socket would carry."""

    def __init__(self, process, server_addr: str):
        from .sim import Endpoint

        self.process = process
        self.ep = Endpoint(server_addr, "http.request")

    async def round_trip(self, raw_request: bytes) -> bytes:
        return await self.process.request(self.ep, raw_request)


class RealHttpTransport:
    """One short-lived TCP connection per request, driven by the
    RealLoop's readiness callbacks (no threads, no blocking)."""

    def __init__(self, loop, host: str, port: int):
        self.loop = loop
        self.host = host
        self.port = port

    async def round_trip(self, raw_request: bytes) -> bytes:
        import socket

        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setblocking(False)
        fut: Future = Future()
        state = {"out": bytearray(raw_request), "in": bytearray()}

        def fail(e):
            cleanup()
            if not fut.is_ready():
                fut._set_error(e)

        def cleanup():
            try:
                self.loop.remove_reader(sock)
            except Exception:
                pass
            try:
                self.loop.remove_writer(sock)
            except Exception:
                pass

        def on_writable():
            try:
                while state["out"]:
                    n = sock.send(state["out"])
                    if n <= 0:
                        break
                    del state["out"][:n]
            except (BlockingIOError, InterruptedError):
                return
            except OSError as e:
                fail(e)
                return
            if not state["out"]:
                self.loop.remove_writer(sock)

        def on_readable():
            try:
                data = sock.recv(1 << 16)
            except (BlockingIOError, InterruptedError):
                return
            except OSError as e:
                fail(e)
                return
            if data:
                state["in"] += data
                # connection-close framing finishes on EOF; but finish
                # early once Content-Length is satisfied
                parsed = None
                try:
                    parsed = parse_response(bytes(state["in"]))
                except HttpError as e:
                    fail(e)
                    return
                if parsed is None:
                    return
            cleanup()
            if not fut.is_ready():
                try:
                    parsed = parse_response(bytes(state["in"]))
                except HttpError as e:
                    fut._set_error(e)
                    return
                if parsed is None:
                    fut._set_error(HttpError("connection closed mid-response"))
                else:
                    fut._set(bytes(state["in"]))

        try:
            sock.connect((self.host, self.port))
        except BlockingIOError:
            pass
        except OSError as e:
            fail(e)
        self.loop.add_writer(sock, on_writable)
        self.loop.add_reader(sock, on_readable)
        try:
            raw = await fut
        finally:
            cleanup()
            try:
                sock.close()
            except OSError:
                pass
        return raw


class HttpClient:
    """Method helpers over a transport; raises HttpError on non-2xx
    unless the status is in ``ok`` (404 is a normal answer for GETs)."""

    def __init__(self, transport):
        self.transport = transport

    async def request(
        self,
        method: str,
        path: str,
        body: bytes = b"",
        ok: tuple = (200, 204),
    ):
        raw = await self.transport.round_trip(
            encode_request(method, path, body)
        )
        parsed = parse_response(raw if isinstance(raw, bytes) else bytes(raw))
        if parsed is None:
            raise HttpError("truncated response")
        status, headers, rbody = parsed
        if status not in ok:
            raise HttpError(f"{method} {path} -> {status}: {rbody[:200]!r}")
        return status, rbody
