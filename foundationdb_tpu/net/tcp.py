"""Real TCP transport: the non-simulated network personality.

The analog of fdbrpc/FlowTransport.actor.cpp: token-addressed endpoints,
length+CRC framed messages (net/wire.py), a protocol-version handshake on
connect, automatic reconnection, and BrokenPromise semantics for requests
to dead peers — behind the exact ``request()``/``register()`` surface of
the simulator (net/sim.py), so every role runs unmodified as a real OS
process.

Topology objects:

- ``RealWorld`` — one OS process's view of the cluster. Mirrors ``Sim``'s
  surface (``knobs``, ``loop``, ``disk()``, ``processes``/``new_process``)
  so code written against the simulator runs over TCP untouched.
- ``RealNode`` — the local process (mirrors ``SimProcess``): registers
  endpoint handlers, originates requests. One listener per process;
  request/reply frames multiplex over one connection per peer.

Failure semantics match the sim: a request to an unreachable/reset peer
errors with BrokenPromise (flow's broken_promise); callers retry through
their existing failover paths. Errors raised by remote handlers propagate
with their FdbError code; everything else surfaces as RemoteError.
"""

from __future__ import annotations

import socket
from typing import Any, Callable, Optional

from ..errors import FdbError
from ..runtime.futures import ActorCollection, Cancelled, Future, spawn
from ..runtime.knobs import Knobs
from ..runtime.loop import RealLoop, TaskPriority, set_loop
from ..runtime.trace import SevError, SevInfo, SevWarn, trace
from . import wire
from .sim import BrokenPromise, Endpoint


class RemoteError(Exception):
    """A remote handler raised an exception outside the named registry."""


def _named_errors() -> dict:
    """Framework exception classes reconstructed BY NAME across the wire —
    server code catches these by type (e.g. the proxy's TLogStopped
    handling marks the epoch dead), so flattening them to RemoteError in
    the real-TCP personality would silently disable those paths (a
    TLogStopped that stays RemoteError left a fenced proxy serving
    forever — found by the TCP kill/restart soak)."""
    from ..server.tlog import TLogStopped
    from ..server.movekeys import MoveKeysError

    return {
        "TLogStopped": TLogStopped,
        "MoveKeysError": MoveKeysError,
    }


class _Conn:
    """One TCP connection (either direction) with framing + dispatch.

    With a TLS-enabled world the socket is an SSLSocket whose handshake is
    driven HERE, non-blocking (the reference's TLSConnection wraps its
    streams the same way, fdbrpc/TLSConnection.actor.cpp): until the
    handshake completes, reads/writes feed the handshake; the wire
    preamble and frames flow only after it."""

    def __init__(
        self,
        world: "RealWorld",
        sock: socket.socket,
        peer: Optional[str],
        preamble: bytes = b"",
    ):
        self.world = world
        self.sock = sock
        self.peer = peer  # peer's listen address (None until handshake)
        self.inbuf = bytearray()
        # the wire preamble MUST be queued before the TLS drive below: a
        # handshake that completes synchronously flushes the outbuf, and
        # bytes appended afterwards would strand with no writer
        self.outbuf = bytearray(preamble)
        self.closed = False
        self.handshaken = peer is not None and False  # always expect preamble
        self._flush_scheduled = False
        import ssl as _ssl

        self._tls_handshaking = isinstance(sock, _ssl.SSLSocket)
        self._tls_write_wants_read = False
        sock.setblocking(False)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        world.loop.add_reader(sock, self._on_readable)
        if self._tls_handshaking:
            self._drive_tls()

    def _drive_tls(self) -> None:
        import ssl as _ssl

        try:
            self.sock.do_handshake()
        except _ssl.SSLWantReadError:
            return  # reader is always registered
        except _ssl.SSLWantWriteError:
            self.world.loop.add_writer(self.sock, self._on_writable)
            return
        except (_ssl.SSLError, OSError) as e:
            trace(
                SevWarn,
                "TLSHandshakeFailed",
                self.world.node.address,
                Err=str(e)[:200],
            )
            self.close()
            return
        self._tls_handshaking = False
        if self.outbuf:
            self._on_writable()
            if self.outbuf and not self.closed:
                self.world.loop.add_writer(self.sock, self._on_writable)
        # application bytes may have arrived WITH the handshake's last
        # flight and now sit decrypted inside the SSL object — the fd
        # will never signal readable for them again
        pending = getattr(self.sock, "pending", None)
        if not self.closed and pending is not None and pending():
            self._on_readable()

    def send(self, msg: Any) -> None:
        if self.closed:
            return
        self.outbuf += wire.encode_frame(wire.encode_value(msg))
        # coalesced flush: every message queued during THIS loop tick goes
        # out in one send() syscall (the flush runs at ZERO priority after
        # all same-time work — profiling the real cluster put per-message
        # syscalls at ~25% of client CPU). No select() wait intervenes, so
        # latency is unchanged.
        if not self._flush_scheduled and not self._tls_handshaking:
            self._flush_scheduled = True
            self.world.loop.call_soon(self._flush_tick, TaskPriority.ZERO)

    def _flush_tick(self) -> None:
        self._flush_scheduled = False
        if self.closed or self._tls_handshaking:
            return
        # always attempt the flush and (re)arm the writer on leftover:
        # assuming "non-empty outbuf implies a registered writer" once
        # stranded a preamble queued right after a synchronously-
        # completing TLS handshake
        self._on_writable()
        if self.outbuf and not self.closed:
            self.world.loop.add_writer(self.sock, self._on_writable)

    def _on_writable(self) -> None:
        if self._tls_handshaking:
            self.world.loop.remove_writer(self.sock)
            self._drive_tls()
            return
        import ssl as _ssl

        try:
            while self.outbuf:
                n = self.sock.send(self.outbuf)
                if n <= 0:
                    break
                del self.outbuf[:n]
        except _ssl.SSLWantReadError:
            # the SSL layer must READ (a post-handshake record) before
            # this write can proceed; keeping the writer armed would
            # busy-spin on an always-writable fd — retry from the read
            # path instead
            self._tls_write_wants_read = True
            self.world.loop.remove_writer(self.sock)
            return
        except (BlockingIOError, InterruptedError, _ssl.SSLWantWriteError):
            pass
        except OSError:
            self.close()
            return
        if not self.outbuf:
            self.world.loop.remove_writer(self.sock)

    def _on_readable(self) -> None:
        if self._tls_handshaking:
            self._drive_tls()
            if self._tls_handshaking or self.closed:
                return
        if self._tls_write_wants_read and not self.closed:
            # a stalled write was waiting on inbound TLS records
            self._tls_write_wants_read = False
            self._on_writable()
            if self.outbuf and not self.closed and not self._tls_write_wants_read:
                self.world.loop.add_writer(self.sock, self._on_writable)
            if self.closed:
                return
        import ssl as _ssl

        try:
            data = self.sock.recv(1 << 16)
        except (BlockingIOError, InterruptedError, _ssl.SSLWantReadError):
            return
        except (_ssl.SSLWantWriteError,):
            self.world.loop.add_writer(self.sock, self._on_writable)
            return
        except OSError:
            self.close()
            return
        if not data:
            self.close()
            return
        self.inbuf += data
        # drain TLS-internal plaintext: decrypted bytes can sit in the SSL
        # buffer with no fd readiness to re-trigger select
        pending = getattr(self.sock, "pending", None)
        while pending is not None and pending():
            try:
                more = self.sock.recv(1 << 16)
            except (_ssl.SSLWantReadError, BlockingIOError):
                break
            if not more:
                break
            self.inbuf += more
        try:
            if not self.handshaken:
                hs = wire.parse_handshake(self.inbuf)
                if hs is None:
                    return
                addr, consumed = hs
                del self.inbuf[:consumed]
                self.handshaken = True
                if self.peer is None:
                    self.peer = addr
                self.world._conn_ready(self)
            for payload in wire.decode_frames(self.inbuf):
                self.world._on_message(self, wire.decode_value(payload))
        except wire.WireError as e:
            trace(SevWarn, "WireError", self.world.node.address, Err=str(e))
            self.close()

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        self.world.loop.remove_reader(self.sock)
        self.world.loop.remove_writer(self.sock)
        try:
            self.sock.close()
        except OSError:
            pass
        self.world._conn_closed(self)


class RealNode:
    """The local process — SimProcess-compatible surface."""

    def __init__(self, world: "RealWorld", address: str):
        from ..runtime.locality import Locality

        self.world = world
        self.sim = world  # roles access knobs/disk/loop through .sim
        self.address = address
        self.machine = address
        self.locality = Locality.of(address, zone=world.zone, dc=world.dc)
        self.endpoints: dict[str, Callable] = {}
        self.actors = ActorCollection(on_error=self._on_actor_error)
        self.alive = True
        # a real OS process always boots with a fresh memory image, so its
        # in-memory reboot counter is 0; role code may read it either way
        # (SimProcess counts sim reboots for change-id salting)
        self.reboots = 0

    def _on_actor_error(self, err: BaseException) -> None:
        """Unhandled actor death: SevError + traceback, and — when this
        process is a server (fdbserver sets die_on_actor_error) — process
        exit, so supervision/tests see the crash instead of a silent hang
        (the reference's criticalError path, flow/Error.cpp)."""
        import sys
        import traceback as _tb

        tb = "".join(_tb.format_exception(type(err), err, err.__traceback__))
        # BrokenPromise (requests racing deaths) and propagated Cancelled
        # (awaiting a sibling being torn down) are routine — warn, no death
        benign = isinstance(err, (BrokenPromise, Cancelled))
        trace(
            SevWarn if benign else SevError,
            "UnhandledActorError",
            self.address,
            Err=repr(err),
            Backtrace=tb[-2000:],
        )
        if self.world.die_on_actor_error and not benign:
            print(
                f"fatal: unhandled actor error on {self.address}:\n{tb}",
                file=sys.stderr,
                flush=True,
            )
            import os

            os._exit(44)

    def register(self, token: str, handler: Callable) -> Endpoint:
        self.endpoints[token] = handler
        return Endpoint(self.address, token)

    def spawn(
        self, coro, priority: int = TaskPriority.DEFAULT, name: str = None
    ) -> Future:
        fut = spawn(coro, priority, name)
        self.actors.add(fut)
        return fut

    def request(self, ep: Endpoint, payload: Any) -> Future:
        return self.world.request(ep, payload)


class RealWorld:
    """One OS process's cluster world over TCP (Sim-compatible surface)."""

    def __init__(
        self,
        listen_addr: str,
        knobs: Optional[Knobs] = None,
        data_dir: Optional[str] = None,
        loop: Optional[RealLoop] = None,
        seed: Optional[int] = None,
        zone: Optional[str] = None,
        dc: str = "dc0",
        die_on_actor_error: bool = False,
        tls: Optional[dict] = None,  # {certfile, keyfile, cafile}
    ):
        self.loop = loop or RealLoop(seed)
        self.knobs = knobs or Knobs()
        self.die_on_actor_error = die_on_actor_error
        # mutual TLS (the reference's TLS plugin, fdbrpc/TLSConnection):
        # every connection in either direction presents the cluster cert
        # and verifies the peer against the cluster CA — plaintext peers
        # cannot join or talk to a TLS cluster
        self._tls_server_ctx = self._tls_client_ctx = None
        if tls:
            import ssl as _ssl

            sctx = _ssl.SSLContext(_ssl.PROTOCOL_TLS_SERVER)
            sctx.load_cert_chain(tls["certfile"], tls["keyfile"])
            sctx.load_verify_locations(tls["cafile"])
            sctx.verify_mode = _ssl.CERT_REQUIRED
            cctx = _ssl.SSLContext(_ssl.PROTOCOL_TLS_CLIENT)
            cctx.load_cert_chain(tls["certfile"], tls["keyfile"])
            cctx.load_verify_locations(tls["cafile"])
            cctx.check_hostname = False  # peers are addressed by ip:port
            cctx.verify_mode = _ssl.CERT_REQUIRED
            self._tls_server_ctx, self._tls_client_ctx = sctx, cctx
        self.data_dir = data_dir
        self.zone = zone
        self.dc = dc
        self.node = RealNode(self, listen_addr)
        # Sim-surface compatibility (Database, roles):
        self.processes = {listen_addr: self.node}
        self._disks: dict[str, Any] = {}
        self._conns: dict[str, _Conn] = {}  # peer listen addr → live conn
        self._connecting: dict[str, Future] = {}
        self._anon: list[_Conn] = []  # accepted, pre-handshake
        self._pending: dict[int, tuple[Future, str]] = {}  # id → (fut, peer)
        self._disconnect_watchers: list[Callable[[str], None]] = []
        self._next_id = 1
        self._listener: Optional[socket.socket] = None
        self._listen()
        # run-loop profiler, REAL personality: wall busy/starvation + the
        # SlowTask trace events. Installed after _listen so the ident is
        # the node's final address (ephemeral ports are adopted there);
        # several worlds may share one loop — the first install wins
        from ..runtime import profiler as _profiler

        _profiler.install(
            self.loop, knobs=self.knobs, wall=True, ident=self.node.address
        )

    # -- Sim-compatible world surface -----------------------------------------

    def new_process(self, address: str, machine: str = None, boot=None) -> RealNode:
        """A real OS process hosts exactly one node; Database asks for a
        'client' process and gets the local one."""
        return self.node

    def disk(self, machine: str):
        from .files import RealDisk

        d = self._disks.get(machine)
        if d is None:
            import os

            root = self.data_dir or "fdbtpu-data"
            d = self._disks[machine] = RealDisk(os.path.join(root, machine))
        return d

    def activate(self) -> None:
        set_loop(self.loop)

    def run(self, until: float = float("inf"), stop_when=None) -> float:
        self.activate()
        return self.loop.run(until, stop_when)

    def run_until_done(self, fut: Future, limit: float = 1e9) -> Any:
        self.activate()
        t0 = self.loop.now()
        self.loop.run(until=t0 + limit, stop_when=fut.is_ready)
        if not fut.is_ready():
            raise TimeoutError(f"did not finish within {limit}s")
        return fut.get()

    def close(self) -> None:
        if self._listener is not None:
            self.loop.remove_reader(self._listener)
            self._listener.close()
            self._listener = None
        for c in list(self._conns.values()) + list(self._anon):
            c.close()

    # -- listening -------------------------------------------------------------

    def _listen(self) -> None:
        host, port = self.node.address.rsplit(":", 1)
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, int(port)))
        if int(port) == 0:
            # ephemeral port (clients like fdbcli): adopt the real one as
            # this node's identity before anything handshakes with it
            real = s.getsockname()[1]
            addr = f"{host}:{real}"
            self.processes[addr] = self.processes.pop(self.node.address)
            self.node.address = addr
            self.node.machine = addr
        s.listen(128)
        s.setblocking(False)
        self._listener = s
        self.loop.add_reader(s, self._on_accept)
        trace(SevInfo, "TransportListening", self.node.address)

    def _on_accept(self) -> None:
        while True:
            try:
                sock, _addr = self._listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            if self._tls_server_ctx is not None:
                try:
                    sock.setblocking(False)
                    sock = self._tls_server_ctx.wrap_socket(
                        sock, server_side=True, do_handshake_on_connect=False
                    )
                except Exception as e:
                    trace(
                        SevWarn,
                        "TLSAcceptFailed",
                        self.node.address,
                        Err=str(e)[:200],
                    )
                    sock.close()
                    continue
            conn = _Conn(
                self, sock, None, preamble=wire.handshake_bytes(self.node.address)
            )
            if not conn._tls_handshaking and not conn.closed:
                conn._on_writable()
                if conn.outbuf and not conn.closed:
                    self.loop.add_writer(sock, conn._on_writable)
            if not conn.closed:
                self._anon.append(conn)

    # -- connections -----------------------------------------------------------

    def _conn_ready(self, conn: _Conn) -> None:
        if conn in self._anon:
            self._anon.remove(conn)
        # simultaneous connect: the newest handshaken connection wins the
        # routing slot; a displaced one still drains its in-flight replies
        # until either side closes it
        self._conns[conn.peer] = conn
        waiter = self._connecting.pop(conn.peer, None)
        if waiter is not None and not waiter.is_ready():
            waiter._set(None)

    def _conn_closed(self, conn: _Conn) -> None:
        if conn in self._anon:
            self._anon.remove(conn)
        if conn.peer is not None and self._conns.get(conn.peer) is conn:
            del self._conns[conn.peer]
        # fail requests that were in flight on this connection
        dead = [
            (rid, fut)
            for rid, (fut, peer) in self._pending.items()
            if peer == conn.peer
        ]
        for rid, fut in dead:
            self._pending.pop(rid, None)
            if not fut.is_ready():
                fut._set_error(BrokenPromise(f"connection to {conn.peer} lost"))
        waiter = self._connecting.pop(conn.peer, None) if conn.peer else None
        if waiter is not None and not waiter.is_ready():
            waiter._set_error(BrokenPromise(f"connect to {conn.peer} failed"))
        # failure-monitor hook (the reference wires connection failure into
        # SimpleFailureMonitor, FlowTransport.actor.cpp): subscribers learn
        # about a dead peer immediately instead of waiting out heartbeats
        if conn.peer is not None:
            for cb in list(self._disconnect_watchers):
                try:
                    cb(conn.peer)
                except Exception:
                    pass

    def on_peer_disconnect(self, cb: Callable[[str], None]) -> None:
        """Register a connection-failure callback (peer listen address)."""
        self._disconnect_watchers.append(cb)

    def _connect(self, peer: str) -> Future:
        """Future resolving when a connection to ``peer`` is live."""
        if peer in self._conns:
            f = Future()
            f._set(None)
            return f
        waiter = self._connecting.get(peer)
        if waiter is not None:
            return waiter
        waiter = self._connecting[peer] = Future()
        host, port = peer.rsplit(":", 1)
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setblocking(False)
        try:
            sock.connect((host, int(port)))
        except (BlockingIOError, InterruptedError):
            pass
        except OSError as e:
            sock.close()
            self._connecting.pop(peer, None)
            waiter._set_error(BrokenPromise(f"connect {peer}: {e}"))
            return waiter

        if self._tls_client_ctx is not None:
            # TLS: the _Conn (and its SSL wrap) exists only once the TCP
            # connect completes; until then failures resolve the waiter
            # directly
            def on_tcp_connected():
                self.loop.remove_writer(sock)
                err = sock.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
                if err:
                    sock.close()
                    self._connecting.pop(peer, None)
                    if not waiter.is_ready():
                        waiter._set_error(
                            BrokenPromise(f"connect to {peer} failed")
                        )
                    return
                try:
                    wrapped = self._tls_client_ctx.wrap_socket(
                        sock, do_handshake_on_connect=False
                    )
                except Exception as e:
                    sock.close()
                    self._connecting.pop(peer, None)
                    if not waiter.is_ready():
                        waiter._set_error(BrokenPromise(f"tls {peer}: {e}"))
                    return
                _Conn(
                    self,
                    wrapped,
                    peer,
                    preamble=wire.handshake_bytes(self.node.address),
                )

            self.loop.add_writer(sock, on_tcp_connected)
            return waiter

        # queue our preamble NOW: on localhost the peer's preamble can
        # arrive (and resolve the connect waiter) before the writability
        # callback below ever runs — a request sent at that moment must
        # find the handshake already ahead of it in the buffer, or the
        # first frame beats the preamble onto the wire
        conn = _Conn(
            self, sock, peer, preamble=wire.handshake_bytes(self.node.address)
        )

        def on_connected():
            if conn.closed:
                return  # read side already saw the failure in this batch
            self.loop.remove_writer(sock)
            err = sock.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
            if err:
                conn.close()
                return
            try:
                conn._on_writable()
                if conn.outbuf:
                    self.loop.add_writer(sock, conn._on_writable)
            except OSError:
                conn.close()

        self.loop.add_writer(sock, on_connected)
        return waiter

    # -- RPC -------------------------------------------------------------------

    def request(self, ep: Endpoint, payload: Any) -> Future:
        from ..runtime import trace as _trace

        reply: Future = Future()
        if ep.address == self.node.address:
            self._dispatch_local(ep.token, payload, reply)
            return reply
        rid = self._next_id
        self._next_id += 1
        # the caller's span context rides the request tuple (the analog of
        # FlowTransport's SpanContextMessage): the remote handler runs as a
        # child of the caller's span without the payload knowing
        msg = ("req", rid, ep.token, payload, wire.pack_span_context(_trace.active_span()))
        conn = self._conns.get(ep.address)
        if conn is not None:
            self._pending[rid] = (reply, ep.address)
            conn.send(msg)
            return reply

        waiter = self._connect(ep.address)

        def on_conn():
            if waiter.is_error():
                if not reply.is_ready():
                    reply._set_error(waiter._error)
                return
            c = self._conns.get(ep.address)
            if c is None:
                if not reply.is_ready():
                    reply._set_error(BrokenPromise(f"no route to {ep.address}"))
                return
            self._pending[rid] = (reply, ep.address)
            c.send(msg)

        waiter.add_callback(lambda _f: on_conn())
        return reply

    def _dispatch_local(self, token: str, payload, reply: Future) -> None:
        handler = self.node.endpoints.get(token)
        if handler is None:
            reply._set_error(BrokenPromise(f"{self.node.address}:{token}"))
            return

        async def run_and_reply():
            try:
                result = await handler(payload)
            except Cancelled:
                if not reply.is_ready():
                    reply._set_error(BrokenPromise(token))
                return
            except BaseException as e:
                if not reply.is_ready():
                    reply._set_error(e)
                return
            if not reply.is_ready():
                reply._set(result)

        # profiler attribution names the handler, not the dispatch shim
        self.node.spawn(
            run_and_reply(), name=getattr(handler, "__qualname__", None)
        )

    def _on_message(self, conn: _Conn, msg) -> None:
        kind = msg[0]
        if kind == "req":
            _k, rid, token, payload, *rest = msg
            handler = self.node.endpoints.get(token)
            if handler is None:
                conn.send(("err", rid, "broken_promise", token))
                return
            span_ctx = wire.unpack_span_context(rest[0]) if rest else None

            async def run_and_reply(rid=rid, handler=handler, payload=payload):
                try:
                    result = await handler(payload)
                except Cancelled:
                    conn.send(("err", rid, "broken_promise", token))
                    return
                except FdbError as e:
                    conn.send(("err", rid, "fdb", type(e).__name__))
                    return
                except BrokenPromise as e:
                    conn.send(("err", rid, "broken_promise", str(e)))
                    return
                except BaseException as e:
                    if type(e).__name__ in _named_errors():
                        conn.send(
                            ("err", rid, "named", (type(e).__name__, str(e)))
                        )
                        return
                    conn.send(("err", rid, "remote", repr(e)))
                    return
                conn.send(("ok", rid, result))

            from ..runtime import trace as _trace

            prev = _trace.swap_active_span(span_ctx)
            try:
                # profiler attribution names the handler, not the shim
                self.node.spawn(
                    run_and_reply(), name=getattr(handler, "__qualname__", None)
                )
            finally:
                _trace.swap_active_span(prev)
        elif kind == "ok":
            _k, rid, value = msg
            ent = self._pending.pop(rid, None)
            if ent is not None and not ent[0].is_ready():
                ent[0]._set(value)
        elif kind == "err":
            _k, rid, etype, detail = msg
            ent = self._pending.pop(rid, None)
            if ent is None or ent[0].is_ready():
                return
            if etype == "fdb":
                from .. import errors as _errors

                cls = getattr(_errors, str(detail), FdbError)
                if not (isinstance(cls, type) and issubclass(cls, FdbError)):
                    cls = FdbError
                ent[0]._set_error(cls(str(detail)))
            elif etype == "broken_promise":
                ent[0]._set_error(BrokenPromise(str(detail)))
            elif etype == "named":
                name, text = detail
                cls = _named_errors().get(str(name), RemoteError)
                ent[0]._set_error(cls(str(text)))
            else:
                ent[0]._set_error(RemoteError(str(detail)))
        else:
            trace(SevWarn, "WireBadKind", self.node.address, Kind=str(kind))
