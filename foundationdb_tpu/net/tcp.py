"""Real TCP transport: the non-simulated network personality.

The analog of fdbrpc/FlowTransport.actor.cpp: token-addressed endpoints,
length+CRC framed messages (net/wire.py), a protocol-version handshake on
connect, automatic reconnection, and BrokenPromise semantics for requests
to dead peers — behind the exact ``request()``/``register()`` surface of
the simulator (net/sim.py), so every role runs unmodified as a real OS
process.

Transport v2 (ISSUE 14): the wire path is frame-batched and zero-copy —
preallocated receive buffers filled by ``recv_into`` and parsed as
``memoryview`` slices (wire.RecvBuffer), send queues with consumed-offset
compaction instead of per-send ``del buf[:n]`` (wire.SendBuffer), and ONE
gen-7 super-frame per connection per loop tick carrying every message
coalesced in that tick, flushed with a vectored ``sendmsg`` when the
socket allows. Inbound super-frames batch-dispatch: one loop step drains
every request in the frame (futures.start_batch) instead of scheduling a
wakeup per request. Colocated worlds in the same OS process short-circuit
onto the in-process loopback transport (net/loopback.py) automatically.
The ``TRANSPORT_*`` knobs keep the gen-6-shaped path (per-message frames,
sockets everywhere) available for A/B.

Topology objects:

- ``RealWorld`` — one OS process's view of the cluster. Mirrors ``Sim``'s
  surface (``knobs``, ``loop``, ``disk()``, ``processes``/``new_process``)
  so code written against the simulator runs over TCP untouched.
- ``RealNode`` — the local process (mirrors ``SimProcess``): registers
  endpoint handlers, originates requests. One listener per process;
  request/reply frames multiplex over one connection per peer.

Failure semantics match the sim: a request to an unreachable/reset peer
errors with BrokenPromise (flow's broken_promise); callers retry through
their existing failover paths. Errors raised by remote handlers propagate
with their FdbError code; everything else surfaces as RemoteError.
"""

from __future__ import annotations

import socket
from typing import Any, Callable, Optional

from ..errors import FdbError
from ..runtime.futures import (
    ActorCollection,
    Cancelled,
    Future,
    Task,
    settle_batch,
    spawn,
    start_batch,
)
from ..runtime.knobs import Knobs
from ..runtime.loop import RealLoop, TaskPriority, set_loop
from ..runtime.trace import SevError, SevInfo, SevWarn, trace
from . import loopback, wire
from .metrics import TransportMetrics
from .sim import BrokenPromise, Endpoint


class RemoteError(Exception):
    """A remote handler raised an exception outside the named registry."""


def _named_errors() -> dict:
    """Framework exception classes reconstructed BY NAME across the wire —
    server code catches these by type (e.g. the proxy's TLogStopped
    handling marks the epoch dead), so flattening them to RemoteError in
    the real-TCP personality would silently disable those paths (a
    TLogStopped that stays RemoteError left a fenced proxy serving
    forever — found by the TCP kill/restart soak)."""
    from ..server.tlog import TLogStopped
    from ..server.movekeys import MoveKeysError

    return {
        "TLogStopped": TLogStopped,
        "MoveKeysError": MoveKeysError,
    }


class _Conn:
    """One TCP connection (either direction) with framing + dispatch.

    With a TLS-enabled world the socket is an SSLSocket whose handshake is
    driven HERE, non-blocking (the reference's TLSConnection wraps its
    streams the same way, fdbrpc/TLSConnection.actor.cpp): until the
    handshake completes, reads/writes feed the handshake; the wire
    preamble and frames flow only after it."""

    def __init__(
        self,
        world: "RealWorld",
        sock: socket.socket,
        peer: Optional[str],
        preamble: bytes = b"",
    ):
        self.world = world
        self.sock = sock
        self.peer = peer  # peer's listen address (None until handshake)
        knobs = world.knobs
        self.metrics = world.transport_metrics
        self._rb = wire.RecvBuffer(
            knobs.TRANSPORT_RECV_BYTES, knobs.TRANSPORT_COMPACT_WATERMARK
        )
        # the wire preamble MUST be queued before the TLS drive below: a
        # handshake that completes synchronously flushes the send queue,
        # and bytes appended afterwards would strand with no writer
        self._out = wire.SendBuffer(knobs.TRANSPORT_COMPACT_WATERMARK)
        if preamble:
            self._out.append(preamble)
        self.metrics.track_buffer(self._rb)
        self.metrics.track_buffer(self._out)
        self.metrics.connections.add(1)
        # gen-7 frame batching: encoded messages collect here per tick and
        # flush as ONE super-frame (knob off = per-message gen-6 framing)
        self._batching = bool(knobs.TRANSPORT_FRAME_BATCHING)
        self._batch_cap = max(int(knobs.TRANSPORT_MAX_BATCH_MESSAGES), 1)
        self._pending_msgs: list[bytes] = []
        self.closed = False
        self.handshaken = peer is not None and False  # always expect preamble
        self._flush_scheduled = False
        import ssl as _ssl

        self._tls_handshaking = isinstance(sock, _ssl.SSLSocket)
        self._tls_write_wants_read = False
        # vectored flush only on plain sockets (SSLSocket *exposes*
        # sendmsg but raises NotImplementedError at call time)
        self._sendmsg = (
            None
            if isinstance(sock, _ssl.SSLSocket)
            else getattr(sock, "sendmsg", None)
        )
        sock.setblocking(False)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        world.loop.add_reader(sock, self._on_readable)
        if self._tls_handshaking:
            self._drive_tls()

    def _drive_tls(self) -> None:
        import ssl as _ssl

        try:
            self.sock.do_handshake()
        except _ssl.SSLWantReadError:
            return  # reader is always registered
        except _ssl.SSLWantWriteError:
            self.world.loop.add_writer(self.sock, self._on_writable)
            return
        except (_ssl.SSLError, OSError) as e:
            trace(
                SevWarn,
                "TLSHandshakeFailed",
                self.world.node.address,
                Err=str(e)[:200],
            )
            self.close()
            return
        self._tls_handshaking = False
        if len(self._out):
            self._on_writable()
            if len(self._out) and not self.closed:
                self.world.loop.add_writer(self.sock, self._on_writable)
        # application bytes may have arrived WITH the handshake's last
        # flight and now sit decrypted inside the SSL object — the fd
        # will never signal readable for them again
        pending = getattr(self.sock, "pending", None)
        if not self.closed and pending is not None and pending():
            self._on_readable()

    def send(self, msg: Any) -> None:
        if self.closed:
            return
        payload = wire.encode_value(msg)
        m = self.metrics
        m.messages_sent.add(1)
        m.tcp_messages.add(1)
        if self._batching:
            self._pending_msgs.append(payload)
            if len(self._pending_msgs) >= self._batch_cap:
                self._emit()  # early flush; ordering preserved within the tick
        else:
            frame = wire.encode_frame(payload)
            self._out.append(frame)
            m.frames_sent.add(1)
            m.bytes_sent.add(len(frame))
        # coalesced flush: every message queued during THIS loop tick goes
        # out in one super-frame / one send() syscall (the flush runs at
        # ZERO priority after all same-time work — profiling the real
        # cluster put per-message syscalls at ~25% of client CPU). No
        # select() wait intervenes, so latency is unchanged.
        if not self._flush_scheduled and not self._tls_handshaking:
            self._flush_scheduled = True
            self.world.loop.call_soon(self._flush_tick, TaskPriority.ZERO)

    def _emit(self) -> None:
        """Package this tick's coalesced messages into one wire frame."""
        msgs = self._pending_msgs
        if not msgs:
            return
        self._pending_msgs = []
        m = self.metrics
        m.frames_sent.add(1)
        m.messages_per_flush.add(float(len(msgs)))
        if len(msgs) == 1:
            # a lone message rides the (smaller) gen-6 frame — both decode
            # paths stay exercised on every connection
            frame = wire.encode_frame(msgs[0])
            m.bytes_sent.add(len(frame))
            self._out.append(frame)
            return
        iov = wire.encode_super_frame(msgs)
        nbytes = sum(len(b) for b in iov)
        m.bytes_sent.add(nbytes)
        fault = self.world._flush_fault
        if fault is not None and fault(self):
            # injected partial flush (the sim's transport-truncate analog
            # for real sockets): half the super-frame hits the wire, then
            # the connection dies — the peer must discard the torn frame
            # and every in-flight request must fail typed, not hang
            m.truncation_faults.add(1)
            joined = b"".join(iov)
            try:
                self.sock.send(joined[: len(joined) // 2])
            except OSError:
                pass
            self.close()
            return
        if (
            not len(self._out)
            and self._sendmsg is not None
            and not self._tls_handshaking
            and len(iov) <= 1000  # IOV_MAX headroom
        ):
            # vectored fast path: the whole super-frame leaves in one
            # sendmsg with zero concatenation; a partial send spills the
            # tail into the send queue and the writer picks it up
            try:
                sent = self._sendmsg(iov)
            except (BlockingIOError, InterruptedError):
                sent = 0
            except OSError:
                self.close()
                return
            if sent >= nbytes:
                return
            for buf in iov:
                if sent >= len(buf):
                    sent -= len(buf)
                    continue
                self._out.append(buf[sent:] if sent else buf)
                sent = 0
            return
        for buf in iov:
            self._out.append(buf)

    def _flush_tick(self) -> None:
        self._flush_scheduled = False
        if self.closed or self._tls_handshaking:
            return
        self._emit()
        # always attempt the flush and (re)arm the writer on leftover:
        # assuming "non-empty send queue implies a registered writer" once
        # stranded a preamble queued right after a synchronously-
        # completing TLS handshake
        if len(self._out):
            self._on_writable()
            if len(self._out) and not self.closed:
                self.world.loop.add_writer(self.sock, self._on_writable)

    def _on_writable(self) -> None:
        if self._tls_handshaking:
            self.world.loop.remove_writer(self.sock)
            self._drive_tls()
            return
        import ssl as _ssl

        try:
            while len(self._out):
                n = self.sock.send(self._out.peek())
                if n <= 0:
                    break
                self._out.consume(n)
        except _ssl.SSLWantReadError:
            # the SSL layer must READ (a post-handshake record) before
            # this write can proceed; keeping the writer armed would
            # busy-spin on an always-writable fd — retry from the read
            # path instead
            self._tls_write_wants_read = True
            self.world.loop.remove_writer(self.sock)
            return
        except (BlockingIOError, InterruptedError, _ssl.SSLWantWriteError):
            pass
        except OSError:
            self.close()
            return
        if not len(self._out):
            self.world.loop.remove_writer(self.sock)

    def _on_readable(self) -> None:
        if self._tls_handshaking:
            self._drive_tls()
            if self._tls_handshaking or self.closed:
                return
        if self._tls_write_wants_read and not self.closed:
            # a stalled write was waiting on inbound TLS records
            self._tls_write_wants_read = False
            self._on_writable()
            if len(self._out) and not self.closed and not self._tls_write_wants_read:
                self.world.loop.add_writer(self.sock, self._on_writable)
            if self.closed:
                return
        import ssl as _ssl

        rb = self._rb
        try:
            n = self.sock.recv_into(rb.writable(1 << 16))
        except (BlockingIOError, InterruptedError, _ssl.SSLWantReadError):
            return
        except (_ssl.SSLWantWriteError,):
            self.world.loop.add_writer(self.sock, self._on_writable)
            return
        except OSError:
            self.close()
            return
        if not n:
            self.close()
            return
        rb.commit(n)
        self.metrics.bytes_received.add(n)
        # drain TLS-internal plaintext: decrypted bytes can sit in the SSL
        # buffer with no fd readiness to re-trigger select
        pending = getattr(self.sock, "pending", None)
        while pending is not None and pending():
            try:
                more = self.sock.recv_into(rb.writable(1 << 16))
            except (_ssl.SSLWantReadError, BlockingIOError):
                break
            if not more:
                break
            rb.commit(more)
            self.metrics.bytes_received.add(more)
        try:
            if not self.handshaken:
                hs = wire.parse_handshake(rb.view())
                if hs is None:
                    return
                addr, consumed = hs
                rb.consume(consumed)
                self.handshaken = True
                if self.peer is None:
                    self.peer = addr
                self.world._conn_ready(self)
            views, consumed, n_frames = wire.parse_frames(rb)
            # decode BEFORE consuming: the views alias buffer storage that
            # consumption may compact
            msgs = [wire.decode_value(v) for v in views]
            del views
            rb.consume(consumed)
            if msgs:
                m = self.metrics
                m.frames_received.add(n_frames)
                m.messages_received.add(len(msgs))
                m.tcp_messages.add(len(msgs))
                self.world._on_batch(self, msgs)
        except wire.WireError as e:
            trace(SevWarn, "WireError", self.world.node.address, Err=str(e))
            self.close()

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        self._pending_msgs.clear()
        self.metrics.untrack_buffer(self._rb)
        self.metrics.untrack_buffer(self._out)
        self.metrics.connections_closed.add(1)
        self.world.loop.remove_reader(self.sock)
        self.world.loop.remove_writer(self.sock)
        try:
            self.sock.close()
        except OSError:
            pass
        self.world._conn_closed(self)


class RealNode:
    """The local process — SimProcess-compatible surface."""

    def __init__(self, world: "RealWorld", address: str):
        from ..runtime.locality import Locality

        self.world = world
        self.sim = world  # roles access knobs/disk/loop through .sim
        self.address = address
        self.machine = address
        self.locality = Locality.of(address, zone=world.zone, dc=world.dc)
        self.endpoints: dict[str, Callable] = {}
        self.actors = ActorCollection(on_error=self._on_actor_error)
        self.alive = True
        # a real OS process always boots with a fresh memory image, so its
        # in-memory reboot counter is 0; role code may read it either way
        # (SimProcess counts sim reboots for change-id salting)
        self.reboots = 0

    def _on_actor_error(self, err: BaseException) -> None:
        """Unhandled actor death: SevError + traceback, and — when this
        process is a server (fdbserver sets die_on_actor_error) — process
        exit, so supervision/tests see the crash instead of a silent hang
        (the reference's criticalError path, flow/Error.cpp)."""
        import sys
        import traceback as _tb

        tb = "".join(_tb.format_exception(type(err), err, err.__traceback__))
        # BrokenPromise (requests racing deaths) and propagated Cancelled
        # (awaiting a sibling being torn down) are routine — warn, no death
        benign = isinstance(err, (BrokenPromise, Cancelled))
        trace(
            SevWarn if benign else SevError,
            "UnhandledActorError",
            self.address,
            Err=repr(err),
            Backtrace=tb[-2000:],
        )
        if self.world.die_on_actor_error and not benign:
            print(
                f"fatal: unhandled actor error on {self.address}:\n{tb}",
                file=sys.stderr,
                flush=True,
            )
            import os

            os._exit(44)

    def register(self, token: str, handler: Callable) -> Endpoint:
        self.endpoints[token] = handler
        return Endpoint(self.address, token)

    def spawn(
        self, coro, priority: int = TaskPriority.DEFAULT, name: str = None
    ) -> Future:
        fut = spawn(coro, priority, name)
        self.actors.add(fut)
        return fut

    def request(self, ep: Endpoint, payload: Any) -> Future:
        return self.world.request(ep, payload)


class RealWorld:
    """One OS process's cluster world over TCP (Sim-compatible surface)."""

    def __init__(
        self,
        listen_addr: str,
        knobs: Optional[Knobs] = None,
        data_dir: Optional[str] = None,
        loop: Optional[RealLoop] = None,
        seed: Optional[int] = None,
        zone: Optional[str] = None,
        dc: str = "dc0",
        die_on_actor_error: bool = False,
        tls: Optional[dict] = None,  # {certfile, keyfile, cafile}
    ):
        self.loop = loop or RealLoop(seed)
        self.knobs = knobs or Knobs()
        self.die_on_actor_error = die_on_actor_error
        # mutual TLS (the reference's TLS plugin, fdbrpc/TLSConnection):
        # every connection in either direction presents the cluster cert
        # and verifies the peer against the cluster CA — plaintext peers
        # cannot join or talk to a TLS cluster
        self._tls_server_ctx = self._tls_client_ctx = None
        if tls:
            import ssl as _ssl

            sctx = _ssl.SSLContext(_ssl.PROTOCOL_TLS_SERVER)
            sctx.load_cert_chain(tls["certfile"], tls["keyfile"])
            sctx.load_verify_locations(tls["cafile"])
            sctx.verify_mode = _ssl.CERT_REQUIRED
            cctx = _ssl.SSLContext(_ssl.PROTOCOL_TLS_CLIENT)
            cctx.load_cert_chain(tls["certfile"], tls["keyfile"])
            cctx.load_verify_locations(tls["cafile"])
            cctx.check_hostname = False  # peers are addressed by ip:port
            cctx.verify_mode = _ssl.CERT_REQUIRED
            self._tls_server_ctx, self._tls_client_ctx = sctx, cctx
        self.data_dir = data_dir
        self.zone = zone
        self.dc = dc
        self.node = RealNode(self, listen_addr)
        # Sim-surface compatibility (Database, roles):
        self.processes = {listen_addr: self.node}
        self._disks: dict[str, Any] = {}
        self._conns: dict[str, Any] = {}  # peer listen addr → live conn
        self._connecting: dict[str, Future] = {}
        self._anon: list[_Conn] = []  # accepted, pre-handshake
        self._pending: dict[int, tuple[Future, str]] = {}  # id → (fut, peer)
        self._inflight: dict[str, int] = {}  # peer → requests in flight
        self._disconnect_watchers: list[Callable[[str], None]] = []
        self._next_id = 1
        self._listener: Optional[socket.socket] = None
        # transport counters (net/metrics.py): one collection per world,
        # fed by every connection and the loopback path; the worker's
        # transport.metrics endpoint and status `transport` section pull it
        self.transport_metrics = TransportMetrics(listen_addr)
        # test/chaos hook: callable(conn) -> bool deciding whether THIS
        # flush is torn mid-super-frame (partial flush + connection death)
        self._flush_fault: Optional[Callable[[_Conn], bool]] = None
        # in-process loopback (net/loopback.py): colocated worlds on the
        # same loop bypass sockets entirely. TLS worlds never loop back —
        # their peer-authentication story must not be silently bypassed.
        self._loopback_ok = bool(self.knobs.TRANSPORT_LOOPBACK) and tls is None
        # commit-path codec/settle modes (ISSUE 18). Both are process-wide
        # (the codec registry and the settle slab are module state), so
        # colocated worlds in one process follow the last world's knobs —
        # A/B runs configure every world identically.
        wire.set_compiled_codec(bool(getattr(self.knobs, "WIRE_COMPILED_CODEC", True)))
        from ..runtime import futures as _futures

        _futures.set_slab_settle(bool(getattr(self.knobs, "FUTURE_SLAB_SETTLE", True)))
        self._listen()
        self.transport_metrics.stats.id = self.node.address
        loopback.register(self)
        # run-loop profiler, REAL personality: wall busy/starvation + the
        # SlowTask trace events. Installed after _listen so the ident is
        # the node's final address (ephemeral ports are adopted there);
        # several worlds may share one loop — the first install wins
        from ..runtime import profiler as _profiler

        _profiler.install(
            self.loop, knobs=self.knobs, wall=True, ident=self.node.address
        )

    # -- Sim-compatible world surface -----------------------------------------

    def new_process(self, address: str, machine: str = None, boot=None) -> RealNode:
        """A real OS process hosts exactly one node; Database asks for a
        'client' process and gets the local one."""
        return self.node

    def disk(self, machine: str):
        from .files import RealDisk

        d = self._disks.get(machine)
        if d is None:
            import os

            root = self.data_dir or "fdbtpu-data"
            d = self._disks[machine] = RealDisk(os.path.join(root, machine))
        return d

    def activate(self) -> None:
        set_loop(self.loop)

    def run(self, until: float = float("inf"), stop_when=None) -> float:
        self.activate()
        return self.loop.run(until, stop_when)

    def run_until_done(self, fut: Future, limit: float = 1e9) -> Any:
        self.activate()
        t0 = self.loop.now()
        self.loop.run(until=t0 + limit, stop_when=fut.is_ready)
        if not fut.is_ready():
            raise TimeoutError(f"did not finish within {limit}s")
        return fut.get()

    def close(self) -> None:
        loopback.unregister(self)
        if self._listener is not None:
            self.loop.remove_reader(self._listener)
            self._listener.close()
            self._listener = None
        for c in list(self._conns.values()) + list(self._anon):
            c.close()

    # -- listening -------------------------------------------------------------

    def _listen(self) -> None:
        host, port = self.node.address.rsplit(":", 1)
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, int(port)))
        if int(port) == 0:
            # ephemeral port (clients like fdbcli): adopt the real one as
            # this node's identity before anything handshakes with it
            real = s.getsockname()[1]
            addr = f"{host}:{real}"
            self.processes[addr] = self.processes.pop(self.node.address)
            self.node.address = addr
            self.node.machine = addr
        s.listen(128)
        s.setblocking(False)
        self._listener = s
        self.loop.add_reader(s, self._on_accept)
        trace(SevInfo, "TransportListening", self.node.address)

    def _on_accept(self) -> None:
        while True:
            try:
                sock, _addr = self._listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            if self._tls_server_ctx is not None:
                try:
                    sock.setblocking(False)
                    sock = self._tls_server_ctx.wrap_socket(
                        sock, server_side=True, do_handshake_on_connect=False
                    )
                except Exception as e:
                    trace(
                        SevWarn,
                        "TLSAcceptFailed",
                        self.node.address,
                        Err=str(e)[:200],
                    )
                    sock.close()
                    continue
            conn = _Conn(
                self, sock, None, preamble=wire.handshake_bytes(self.node.address)
            )
            if not conn._tls_handshaking and not conn.closed:
                conn._on_writable()
                if len(conn._out) and not conn.closed:
                    self.loop.add_writer(sock, conn._on_writable)
            if not conn.closed:
                self._anon.append(conn)

    # -- connections -----------------------------------------------------------

    def _conn_ready(self, conn: _Conn) -> None:
        if conn in self._anon:
            self._anon.remove(conn)
        # simultaneous connect: the newest handshaken connection wins the
        # routing slot; a displaced one still drains its in-flight replies
        # until either side closes it
        self._conns[conn.peer] = conn
        waiter = self._connecting.pop(conn.peer, None)
        if waiter is not None and not waiter.is_ready():
            waiter._set(None)

    def _conn_closed(self, conn) -> None:
        if conn in self._anon:
            self._anon.remove(conn)
        if conn.peer is not None and self._conns.get(conn.peer) is conn:
            del self._conns[conn.peer]
        # fail requests that were in flight on this connection
        dead = [
            rid
            for rid, (_fut, peer) in self._pending.items()
            if peer == conn.peer
        ]
        for rid in dead:
            ent = self._pending_pop(rid)
            if ent is not None and not ent[0].is_ready():
                ent[0]._set_error(BrokenPromise(f"connection to {conn.peer} lost"))
        waiter = self._connecting.pop(conn.peer, None) if conn.peer else None
        if waiter is not None and not waiter.is_ready():
            waiter._set_error(BrokenPromise(f"connect to {conn.peer} failed"))
        # failure-monitor hook (the reference wires connection failure into
        # SimpleFailureMonitor, FlowTransport.actor.cpp): subscribers learn
        # about a dead peer immediately instead of waiting out heartbeats
        if conn.peer is not None:
            for cb in list(self._disconnect_watchers):
                try:
                    cb(conn.peer)
                except Exception:
                    pass

    def on_peer_disconnect(self, cb: Callable[[str], None]) -> None:
        """Register a connection-failure callback (peer listen address)."""
        self._disconnect_watchers.append(cb)

    def _connect(self, peer: str) -> Future:
        """Future resolving when a connection to ``peer`` is live."""
        if peer in self._conns:
            f = Future()
            f._set(None)
            return f
        waiter = self._connecting.get(peer)
        if waiter is not None:
            return waiter
        waiter = self._connecting[peer] = Future()
        host, port = peer.rsplit(":", 1)
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setblocking(False)
        try:
            sock.connect((host, int(port)))
        except (BlockingIOError, InterruptedError):
            pass
        except OSError as e:
            sock.close()
            self._connecting.pop(peer, None)
            waiter._set_error(BrokenPromise(f"connect {peer}: {e}"))
            return waiter

        if self._tls_client_ctx is not None:
            # TLS: the _Conn (and its SSL wrap) exists only once the TCP
            # connect completes; until then failures resolve the waiter
            # directly
            def on_tcp_connected():
                self.loop.remove_writer(sock)
                err = sock.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
                if err:
                    sock.close()
                    self._connecting.pop(peer, None)
                    if not waiter.is_ready():
                        waiter._set_error(
                            BrokenPromise(f"connect to {peer} failed")
                        )
                    return
                try:
                    wrapped = self._tls_client_ctx.wrap_socket(
                        sock, do_handshake_on_connect=False
                    )
                except Exception as e:
                    sock.close()
                    self._connecting.pop(peer, None)
                    if not waiter.is_ready():
                        waiter._set_error(BrokenPromise(f"tls {peer}: {e}"))
                    return
                _Conn(
                    self,
                    wrapped,
                    peer,
                    preamble=wire.handshake_bytes(self.node.address),
                )

            self.loop.add_writer(sock, on_tcp_connected)
            return waiter

        # queue our preamble NOW: on localhost the peer's preamble can
        # arrive (and resolve the connect waiter) before the writability
        # callback below ever runs — a request sent at that moment must
        # find the handshake already ahead of it in the buffer, or the
        # first frame beats the preamble onto the wire
        conn = _Conn(
            self, sock, peer, preamble=wire.handshake_bytes(self.node.address)
        )

        def on_connected():
            if conn.closed:
                return  # read side already saw the failure in this batch
            self.loop.remove_writer(sock)
            err = sock.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
            if err:
                conn.close()
                return
            try:
                conn._on_writable()
                if len(conn._out):
                    self.loop.add_writer(sock, conn._on_writable)
            except OSError:
                conn.close()

        self.loop.add_writer(sock, on_connected)
        return waiter

    # -- RPC -------------------------------------------------------------------

    def _pending_add(self, rid: int, fut: Future, peer: str) -> None:
        self._pending[rid] = (fut, peer)
        self._inflight[peer] = self._inflight.get(peer, 0) + 1

    def _pending_pop(self, rid: int):
        ent = self._pending.pop(rid, None)
        if ent is not None:
            peer = ent[1]
            left = self._inflight.get(peer, 0) - 1
            if left > 0:
                self._inflight[peer] = left
            else:
                self._inflight.pop(peer, None)
        return ent

    def request(self, ep: Endpoint, payload: Any) -> Future:
        from ..runtime import trace as _trace

        reply: Future = Future()
        if ep.address == self.node.address:
            self._dispatch_local(ep.token, payload, reply)
            return reply
        rid = self._next_id
        self._next_id += 1
        # the caller's span context rides the request tuple (the analog of
        # FlowTransport's SpanContextMessage): the remote handler runs as a
        # child of the caller's span without the payload knowing
        msg = ("req", rid, ep.token, payload, wire.pack_span_context(_trace.active_span()))
        conn = self._conns.get(ep.address)
        if conn is None and self._loopback_ok:
            target = loopback.lookup(ep.address)
            if (
                target is not None
                and target is not self
                and getattr(target, "_loopback_ok", False)
                and target.loop is self.loop
                and target._listener is not None
            ):
                conn = loopback.connect(self, target)
        if conn is not None:
            # connection-level pipelining: requests never wait for replies;
            # the depth sample is the in-flight count this one joined
            self.transport_metrics.pipelined_depth.add(
                float(self._inflight.get(ep.address, 0))
            )
            self._pending_add(rid, reply, ep.address)
            conn.send(msg)
            return reply

        waiter = self._connect(ep.address)

        def on_conn():
            if waiter.is_error():
                if not reply.is_ready():
                    reply._set_error(waiter._error)
                return
            c = self._conns.get(ep.address)
            if c is None:
                if not reply.is_ready():
                    reply._set_error(BrokenPromise(f"no route to {ep.address}"))
                return
            self._pending_add(rid, reply, ep.address)
            c.send(msg)

        waiter.add_callback(lambda _f: on_conn())
        return reply

    def _dispatch_local(self, token: str, payload, reply: Future) -> None:
        handler = self.node.endpoints.get(token)
        if handler is None:
            reply._set_error(BrokenPromise(f"{self.node.address}:{token}"))
            return

        async def run_and_reply():
            try:
                result = await handler(payload)
            except Cancelled:
                if not reply.is_ready():
                    reply._set_error(BrokenPromise(token))
                return
            except BaseException as e:
                if not reply.is_ready():
                    reply._set_error(e)
                return
            if not reply.is_ready():
                reply._set(result)

        # profiler attribution names the handler, not the dispatch shim
        self.node.spawn(
            run_and_reply(), name=getattr(handler, "__qualname__", None)
        )

    async def _run_and_reply(self, conn, rid: int, token: str, handler, payload):
        try:
            result = await handler(payload)
        except Cancelled:
            conn.send(("err", rid, "broken_promise", token))
            return
        except FdbError as e:
            conn.send(("err", rid, "fdb", type(e).__name__))
            return
        except BrokenPromise as e:
            conn.send(("err", rid, "broken_promise", str(e)))
            return
        except BaseException as e:
            if type(e).__name__ in _named_errors():
                conn.send(("err", rid, "named", (type(e).__name__, str(e))))
                return
            conn.send(("err", rid, "remote", repr(e)))
            return
        conn.send(("ok", rid, result))

    def _on_batch(self, conn, msgs: list) -> None:
        """Batch dispatch for one inbound frame (or loopback drain):
        the frame's REQUESTS all start in a single loop step
        (futures.start_batch) — N handler wakeups collapse into one,
        which is where the per-request wakeup tax went (run-loop profiler
        evidence, ISSUE 14) — and the frame's REPLIES batch-settle the
        same way (futures.settle_batch): one super-frame of N reply
        payloads resumes its N waiter tasks via per-priority
        call_soon_batch entries instead of N individual wakeups."""
        from ..runtime import trace as _trace

        tasks: list[Task] = []
        settles: list = []  # (caller future, value, error)
        for msg in msgs:
            kind = msg[0]
            if kind == "req":
                _k, rid, token, payload, *rest = msg
                handler = self.node.endpoints.get(token)
                if handler is None:
                    conn.send(("err", rid, "broken_promise", token))
                    continue
                span_ctx = wire.unpack_span_context(rest[0]) if rest else None
                prev = _trace.swap_active_span(span_ctx)
                try:
                    # profiler attribution names the handler, not the shim
                    t = Task(
                        self._run_and_reply(conn, rid, token, handler, payload),
                        name=getattr(handler, "__qualname__", None),
                    )
                finally:
                    _trace.swap_active_span(prev)
                self.node.actors.add(t.future)
                tasks.append(t)
            elif kind == "ok":
                _k, rid, value = msg
                ent = self._pending_pop(rid)
                if ent is not None and not ent[0].is_ready():
                    settles.append((ent[0], value, None))
            elif kind == "err":
                _k, rid, etype, detail = msg
                ent = self._pending_pop(rid)
                if ent is not None and not ent[0].is_ready():
                    settles.append((ent[0], None, self._reply_exc(etype, detail)))
            else:
                trace(SevWarn, "WireBadKind", self.node.address, Kind=str(kind))
        start_batch(tasks)
        settle_batch(settles)

    def _on_message(self, conn, msg) -> None:
        self._on_batch(conn, [msg])

    @staticmethod
    def _reply_exc(etype, detail) -> BaseException:
        """Reconstruct the caller-side exception for an ``err`` reply."""
        if etype == "fdb":
            from .. import errors as _errors

            cls = getattr(_errors, str(detail), FdbError)
            if not (isinstance(cls, type) and issubclass(cls, FdbError)):
                cls = FdbError
            return cls(str(detail))
        if etype == "broken_promise":
            return BrokenPromise(str(detail))
        if etype == "named":
            name, text = detail
            cls = _named_errors().get(str(name), RemoteError)
            return cls(str(text))
        return RemoteError(str(detail))
