"""BackupContainer: where backups live.

The analog of fdbclient/BackupContainer.actor.cpp: an abstraction over the
backup destination holding range-snapshot files, mutation-log files, and a
metadata document. Backed by a SimDisk (deterministic tests) or a RealDisk
directory (the `file://` container of the reference; a blob-store backend
slots in behind the same interface)."""

from __future__ import annotations

import json

from ..runtime.serialize import BinaryReader, BinaryWriter


class BackupContainer:
    def __init__(self, disk, name: str):
        self.disk = disk
        self.name = name
        # continue after existing log files — two container handles on the
        # same backup must not overwrite each other's chunks
        self._log_seq = 0
        for fname in self.disk.list():
            if fname.startswith(f"{name}.log."):
                self._log_seq = max(
                    self._log_seq, int(fname.rsplit(".", 1)[1]) + 1
                )

    async def reset(self) -> None:
        """Delete every file of this backup (a fresh submit must not merge
        with a previous same-name run's chunks at restore time)."""
        for fname in list(self.disk.list()):
            if fname.startswith(f"{self.name}."):
                self.disk.remove(fname)
        self._log_seq = 0

    # -- metadata --------------------------------------------------------------

    async def write_meta(self, meta: dict) -> None:
        f = self.disk.open(f"{self.name}.meta.json")
        blob = json.dumps(meta).encode()
        await f.truncate(0)
        await f.write(0, blob)
        await f.sync()

    async def read_meta(self) -> dict:
        f = self.disk.open(f"{self.name}.meta.json")
        raw = await f.read(0, f.size())
        return json.loads(raw.decode()) if raw else {}

    # -- range snapshot files --------------------------------------------------

    async def write_snapshot_chunk(self, index: int, rows: list) -> None:
        w = BinaryWriter()
        w.u32(len(rows))
        for k, v in rows:
            w.bytes_(k).bytes_(v)
        f = self.disk.open(f"{self.name}.snap.{index:06d}")
        await f.truncate(0)
        await f.write(0, w.data())
        await f.sync()

    async def read_snapshot(self) -> list:
        rows = []
        for fname in sorted(self.disk.list()):
            if not fname.startswith(f"{self.name}.snap."):
                continue
            f = self.disk.open(fname)
            r = BinaryReader(await f.read(0, f.size()))
            n = r.u32()
            for _ in range(n):
                rows.append((r.bytes_(), r.bytes_()))
        return rows

    # -- mutation-log files ----------------------------------------------------

    async def append_log_chunk(self, entries: list) -> None:
        """entries: [(log_key, serialized_mutation)] in key (version) order."""
        w = BinaryWriter()
        w.u32(len(entries))
        for k, v in entries:
            w.bytes_(k).bytes_(v)
        f = self.disk.open(f"{self.name}.log.{self._log_seq:06d}")
        self._log_seq += 1
        await f.truncate(0)
        await f.write(0, w.data())
        await f.sync()

    async def read_log(self) -> list:
        entries = []
        for fname in sorted(self.disk.list()):
            if not fname.startswith(f"{self.name}.log."):
                continue
            f = self.disk.open(fname)
            r = BinaryReader(await f.read(0, f.size()))
            n = r.u32()
            for _ in range(n):
                entries.append((r.bytes_(), r.bytes_()))
        entries.sort()  # log keys embed the version: sorts into commit order
        return entries
