"""Backup and DR agents.

BackupAgent — the analog of fdbclient/FileBackupAgent.actor.cpp: a backup
is (1) a mutation-log capture registered under \\xff/logRanges/ (the
proxies duplicate committed mutations into the \\xff\\x02 backup-log
keyspace from that moment), then (2) a consistent range snapshot taken
chunk-by-chunk through TaskBucket tasks, while (3) a drain loop moves the
accumulating log keyspace into the container. Because the capture starts
BEFORE the snapshot version, snapshot + log replay reconstructs any
version ≥ the snapshot's — the reference's restorability invariant.

DrAgent — the analog of fdbclient/DatabaseBackupAgent.actor.cpp: the same
capture machinery, but the drain applies mutations to a second cluster
instead of files, giving asynchronous cluster-to-cluster replication.

Restore replays container contents: clear the range, load the snapshot,
apply the mutation log in version order (fdbrestore).
"""

from __future__ import annotations

import struct

from ..kv.mutations import Mutation, MutationType
from ..layers.subspace import Subspace
from ..layers.taskbucket import TaskBucket, run_agent
from ..runtime.futures import Future, delay
from ..runtime.serialize import BinaryReader, read_mutation
from ..server.systemdata import (
    BACKUP_LOG_PREFIX,
    log_ranges_key,
    log_ranges_value,
)

SNAPSHOT_CHUNK_ROWS = 1000
DRAIN_BATCH = 500


class _CaptureBase:
    def __init__(self, db, uid: str, begin: bytes = b"", end=b"\xff"):
        self.db = db
        self.uid = uid
        self.begin = begin
        self.end = end
        self.dest = BACKUP_LOG_PREFIX + uid.encode() + b"/"
        self.stopped = Future()

    async def _start_capture(self) -> None:
        async def body(tr):
            tr.set(
                log_ranges_key(self.uid),
                log_ranges_value(self.begin, self.end, self.dest),
            )

        await self.db.run(body)

    async def _stop_capture(self) -> None:
        async def body(tr):
            tr.clear(log_ranges_key(self.uid))

        await self.db.run(body)

    async def _drain_chunk(self):
        """Pop up to DRAIN_BATCH captured log entries, in version order."""

        async def body(tr):
            rows = await tr.get_range(
                self.dest, self.dest + b"\xff", limit=DRAIN_BATCH
            )
            for k, _v in rows:
                tr.clear(k)
            return rows

        return await self.db.run(body)


class BackupAgent(_CaptureBase):
    def __init__(self, db, container, uid: str = "backup", begin=b"", end=b"\xff"):
        super().__init__(db, uid, begin, end)
        self.container = container
        self.bucket = TaskBucket(
            Subspace(raw_prefix=b"\xff\x02/tasks/" + uid.encode() + b"/")
        )
        self._drainer = None
        self._worker = None

    async def submit(self) -> None:
        """Start the backup: begin the capture, queue snapshot tasks, and
        run the drain + task agents (submitBackup + the agent loops)."""
        await self.container.reset()  # stale files of a prior same-name run
        await self._start_capture()
        # snapshot version: one consistent cut ≥ capture start; every
        # chunk task reads AT this version (a per-chunk version would make
        # the log-replay boundary ill-defined and double-apply atomics)
        async def snap_meta(tr):
            await tr.get_read_version()
            return tr._read_version

        snapshot_version = await self.db.run(snap_meta)
        self._snapshot_version = snapshot_version
        await self.container.write_meta(
            {
                "uid": self.uid,
                "begin": self.begin.hex(),
                "end": self.end.hex() if self.end is not None else "inf",
                "snapshot_version": snapshot_version,
                "complete_through": None,
            }
        )

        async def queue_task(tr):
            await self.bucket.add_task(
                tr, "snapshot_chunk", begin=self.begin.hex(), index=0
            )

        await self.db.run(queue_task)
        self._worker = self.db.client.spawn(
            run_agent(
                self.db,
                self.bucket,
                {"snapshot_chunk": self._snapshot_chunk},
                self.stopped,
            )
        )
        self._drainer = self.db.client.spawn(self._drain_loop())

    async def _snapshot_chunk(self, db, params) -> None:
        """One chunked range dump at the backup's snapshot version; queues
        its successor (the reference's BackupRangeTaskFunc splitting)."""
        begin = bytes.fromhex(params["begin"])
        index = int(params["index"])

        async def body(tr):
            tr.set_read_version(self._snapshot_version)
            rows = await tr.get_range(
                begin, self.end, limit=SNAPSHOT_CHUNK_ROWS, snapshot=True
            )
            return rows

        rows = await db.run(body)
        await self.container.write_snapshot_chunk(index, rows)
        if len(rows) >= SNAPSHOT_CHUNK_ROWS:
            nxt = rows[-1][0] + b"\x00"

            async def queue_next(tr):
                await self.bucket.add_task(
                    tr, "snapshot_chunk", begin=nxt.hex(), index=index + 1
                )

            await db.run(queue_next)

    async def _drain_loop(self) -> None:
        while not self.stopped.is_ready():
            rows = await self._drain_chunk()
            if rows:
                await self.container.append_log_chunk(rows)
            else:
                await delay(0.5)

    async def wait_snapshot_complete(self, timeout_s: float = 300.0) -> None:
        waited = 0.0
        while not await self.bucket.is_empty(self.db):
            await delay(0.5)
            waited += 0.5
            if waited > timeout_s:
                raise TimeoutError("snapshot tasks did not finish")

    async def discontinue(self) -> None:
        """Stop the backup: end the capture, drain the tail, close out
        (discontinueBackup)."""
        await self._stop_capture()
        while True:
            rows = await self._drain_chunk()
            if not rows:
                break
            await self.container.append_log_chunk(rows)
        self.stopped._set(None)
        meta = await self.container.read_meta()
        meta["complete_through"] = "end"
        await self.container.write_meta(meta)


def _log_entry_version(log_key: bytes) -> int:
    """The commit version embedded in a backup-log key (…<8B version><4B n>)."""
    return struct.unpack(">Q", log_key[-12:-4])[0]


async def restore(db, container) -> int:
    """fdbrestore: clear the target range, load the snapshot, replay the
    mutation log in version order. Log entries at or below the snapshot
    version are already reflected in the snapshot and must be skipped —
    replaying them would double-apply non-idempotent atomic ops. Returns
    rows restored."""
    meta = await container.read_meta()
    begin = bytes.fromhex(meta["begin"])
    end = b"\xff" if meta["end"] == "inf" else bytes.fromhex(meta["end"])
    snapshot_version = meta.get("snapshot_version", 0)
    snapshot = await container.read_snapshot()
    log = [
        (k, v)
        for k, v in await container.read_log()
        if _log_entry_version(k) > snapshot_version
    ]

    async def clear_body(tr):
        tr.clear_range(begin, end)

    await db.run(clear_body)

    for i in range(0, len(snapshot), 500):
        chunk = snapshot[i : i + 500]

        async def load(tr, chunk=chunk):
            for k, v in chunk:
                tr.set(k, v)

        await db.run(load)

    for i in range(0, len(log), 500):
        chunk = log[i : i + 500]

        async def apply(tr, chunk=chunk):
            for _log_key, blob in chunk:
                m = read_mutation(BinaryReader(blob))
                _apply_to_txn(tr, m)

        await db.run(apply)
    # rows loaded + log mutations replayed (a backup begun before any
    # write has an EMPTY snapshot and everything in the log)
    return len(snapshot) + len(log)


def _apply_to_txn(tr, m: Mutation) -> None:
    if m.type == MutationType.SET_VALUE:
        tr.set(m.param1, m.param2)
    elif m.type == MutationType.CLEAR_RANGE:
        tr.clear_range(m.param1, m.param2)
    else:
        tr.atomic_op(m.type, m.param1, m.param2)


class DrAgent(_CaptureBase):
    """Asynchronous replication into a destination cluster: capture on the
    source, apply on the destination (DatabaseBackupAgent)."""

    def __init__(self, src_db, dest_db, uid: str = "dr", begin=b"", end=b"\xff"):
        super().__init__(src_db, uid, begin, end)
        self.dest_db = dest_db
        self._runner = None

    async def start(self, initial_sync: bool = True) -> None:
        await self._start_capture()
        self._sync_version = 0
        if initial_sync:
            # seed the destination with ONE consistent snapshot (the DR
            # "backup" phase): a single source transaction so every row is
            # from the same version; captured entries at or below it are
            # already included and must not re-apply (atomics!)
            async def read_all(tr):
                rows = await tr.get_range(self.begin, self.end, snapshot=True)
                return tr._read_version, rows

            self._sync_version, rows = await self.db.run(read_all)
            for i in range(0, len(rows), SNAPSHOT_CHUNK_ROWS):
                chunk = rows[i : i + SNAPSHOT_CHUNK_ROWS]

                async def write(tr, chunk=chunk):
                    for k, v in chunk:
                        tr.set(k, v)

                await self.dest_db.run(write)
        self._runner = self.db.client.spawn(self._apply_loop())

    async def _apply_rows(self, rows) -> None:
        rows = [
            (k, blob)
            for k, blob in rows
            if _log_entry_version(k) > self._sync_version
        ]
        if not rows:
            return

        async def apply(tr, rows=rows):
            for _k, blob in rows:
                m = read_mutation(BinaryReader(blob))
                _apply_to_txn(tr, m)

        await self.dest_db.run(apply)

    async def _apply_loop(self) -> None:
        while not self.stopped.is_ready():
            rows = await self._drain_chunk()
            if not rows:
                await delay(0.5)
                continue
            await self._apply_rows(rows)

    async def stop(self) -> None:
        await self._stop_capture()
        # final drain
        while True:
            rows = await self._drain_chunk()
            if not rows:
                break
            await self._apply_rows(rows)
        self.stopped._set(None)
