"""Backup and DR: continuous backup to containers, cluster-to-cluster
replication — the analog of fdbclient/FileBackupAgent.actor.cpp,
DatabaseBackupAgent.actor.cpp, BackupContainer.actor.cpp."""

from .container import BackupContainer  # noqa: F401
from .agent import BackupAgent, DrAgent  # noqa: F401
