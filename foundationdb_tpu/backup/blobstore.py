"""S3-style blob store: server core, client, and the backup container.

The analog of fdbrpc/BlobStore.actor.cpp (the `blobstore://` backup
target) + the URL scheme of fdbclient/BackupContainer.actor.cpp:1. The
server core is transport-independent (an object map with bucket/key
paths); it mounts either on a simulated process (blob traffic through
the sim's fault model) or behind a real socket (tools/blobserver). The
API is S3-shaped path-style without auth/XML — documented simplification;
the mechanism (HTTP object PUT/GET/DELETE/LIST behind the container
interface) is what the reference's blob tier provides:

    PUT    /b/<bucket>/<key>          store object
    GET    /b/<bucket>/<key>          fetch object (404 when absent)
    DELETE /b/<bucket>/<key>          delete object
    GET    /b/<bucket>?prefix=<p>     list keys (JSON array)

URL scheme: blobstore://host:port/bucket/name
"""

from __future__ import annotations

import json
import threading
from urllib.parse import quote, unquote, urlparse

from ..net import http
from ..runtime.serialize import BinaryReader, BinaryWriter


class BlobStoreServer:
    """The object map + request handler (transport-independent)."""

    def __init__(self):
        self.objects: dict[tuple[str, str], bytes] = {}
        self._lock = threading.Lock()  # the real server is threaded

    def handle(self, method: str, path: str, body: bytes):
        """(status, body) for one request."""
        path, _, query = path.partition("?")
        parts = [unquote(p) for p in path.split("/") if p]
        if not parts or parts[0] != "b":
            return 400, b"bad path"
        if len(parts) == 2 and method == "GET":
            # list bucket
            prefix = ""
            for kv in query.split("&"):
                k, _, v = kv.partition("=")
                if k == "prefix":
                    prefix = unquote(v)
            bucket = parts[1]
            with self._lock:
                keys = sorted(
                    k
                    for (b, k) in self.objects
                    if b == bucket and k.startswith(prefix)
                )
            return 200, json.dumps(keys).encode()
        if len(parts) < 3:
            return 400, b"bucket/key required"
        bucket, key = parts[1], "/".join(parts[2:])
        with self._lock:
            if method == "PUT":
                self.objects[(bucket, key)] = body
                return 200, b""
            if method == "GET":
                blob = self.objects.get((bucket, key))
                return (200, blob) if blob is not None else (404, b"")
            if method == "DELETE":
                self.objects.pop((bucket, key), None)
                return 200, b""
        return 400, b"bad method"

    def handle_raw(self, raw: bytes) -> bytes:
        parsed = http.parse_request(bytes(raw))
        if parsed is None:
            return http.encode_response(400, b"truncated")
        method, path, _headers, body = parsed
        try:
            status, rbody = self.handle(method, path, body)
        except Exception as e:  # a bad request must not kill the server
            return http.encode_response(500, repr(e).encode())
        return http.encode_response(status, rbody)

    def mount_sim(self, process) -> None:
        """Serve over the simulator's network (http.request endpoint)."""

        async def handler(raw):
            return self.handle_raw(raw)

        process.register("http.request", handler)


class BlobStoreClient:
    def __init__(self, transport, bucket: str):
        self.http = http.HttpClient(transport)
        self.bucket = bucket

    def _path(self, key: str) -> str:
        return f"/b/{quote(self.bucket, safe='')}/{quote(key, safe='/')}"

    async def put(self, key: str, blob: bytes) -> None:
        await self.http.request("PUT", self._path(key), blob)

    async def get(self, key: str):
        status, body = await self.http.request(
            "GET", self._path(key), ok=(200, 404)
        )
        return body if status == 200 else None

    async def delete(self, key: str) -> None:
        await self.http.request("DELETE", self._path(key))

    async def list(self, prefix: str = "") -> list[str]:
        status, body = await self.http.request(
            "GET",
            f"/b/{quote(self.bucket, safe='')}?prefix={quote(prefix, safe='')}",
        )
        return json.loads(body.decode())


def parse_blobstore_url(url: str):
    """blobstore://host:port/bucket/name → (host, port, bucket, name)."""
    u = urlparse(url)
    assert u.scheme == "blobstore", url
    parts = [p for p in u.path.split("/") if p]
    if len(parts) < 2:
        raise ValueError(f"blobstore url needs /bucket/name: {url}")
    return u.hostname, u.port or 80, parts[0], "/".join(parts[1:])


class BlobStoreContainer:
    """BackupContainer surface over a blob store (the `blobstore://`
    personality of fdbclient/BackupContainer.actor.cpp)."""

    def __init__(self, client: BlobStoreClient, name: str):
        self.client = client
        self.name = name
        self._log_seq = None  # discovered lazily (needs an await)

    @classmethod
    def from_url(cls, url: str, transport_factory) -> "BlobStoreContainer":
        host, port, bucket, name = parse_blobstore_url(url)
        transport = transport_factory(host, port)
        return cls(BlobStoreClient(transport, bucket), name)

    async def _next_log_seq(self) -> int:
        if self._log_seq is None:
            seqs = [
                int(k.rsplit("/", 1)[1])
                for k in await self.client.list(f"{self.name}/log/")
            ]
            self._log_seq = max(seqs) + 1 if seqs else 0
        seq = self._log_seq
        self._log_seq += 1
        return seq

    async def reset(self) -> None:
        for k in await self.client.list(f"{self.name}/"):
            await self.client.delete(k)
        self._log_seq = 0

    async def write_meta(self, meta: dict) -> None:
        await self.client.put(
            f"{self.name}/meta.json", json.dumps(meta).encode()
        )

    async def read_meta(self) -> dict:
        blob = await self.client.get(f"{self.name}/meta.json")
        return json.loads(blob.decode()) if blob else {}

    async def write_snapshot_chunk(self, index: int, rows: list) -> None:
        w = BinaryWriter()
        w.u32(len(rows))
        for k, v in rows:
            w.bytes_(k).bytes_(v)
        await self.client.put(f"{self.name}/snap/{index:06d}", w.data())

    async def read_snapshot(self) -> list:
        rows = []
        for key in sorted(await self.client.list(f"{self.name}/snap/")):
            r = BinaryReader(await self.client.get(key))
            n = r.u32()
            for _ in range(n):
                rows.append((r.bytes_(), r.bytes_()))
        return rows

    async def append_log_chunk(self, entries: list) -> None:
        w = BinaryWriter()
        w.u32(len(entries))
        for k, v in entries:
            w.bytes_(k).bytes_(v)
        seq = await self._next_log_seq()
        await self.client.put(f"{self.name}/log/{seq:06d}", w.data())

    async def read_log(self) -> list:
        entries = []
        for key in sorted(await self.client.list(f"{self.name}/log/")):
            r = BinaryReader(await self.client.get(key))
            n = r.u32()
            for _ in range(n):
                entries.append((r.bytes_(), r.bytes_()))
        entries.sort()  # log keys embed the version: commit order
        return entries


def open_container(url_or_name: str, sim=None, process=None, loop=None):
    """Container factory over the URL scheme
    (fdbclient/BackupContainer.actor.cpp:1 openContainer):

      blobstore://host:port/bucket/name  → BlobStoreContainer
        (sim + process → sim transport to the process at `host`;
         loop → real sockets)
      file://dir/name | bare name        → directory-backed container
        (requires sim for the disk)
    """
    if url_or_name.startswith("blobstore://"):
        host, port, bucket, name = parse_blobstore_url(url_or_name)
        if loop is not None:
            transport = http.RealHttpTransport(loop, host, port)
        else:
            assert process is not None, "sim blobstore needs a process"
            transport = http.SimHttpTransport(process, host)
        return BlobStoreContainer(BlobStoreClient(transport, bucket), name)
    from .container import BackupContainer

    name = url_or_name
    if name.startswith("file://"):
        name = name[len("file://"):]
    assert sim is not None, "file container needs the sim's disk"
    disk_name, _, base = name.rpartition("/")
    return BackupContainer(sim.disk(disk_name or "backup-store"), base or name)
